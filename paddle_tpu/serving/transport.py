"""Streaming dataplane: persistent router <-> worker sockets.

PR 10's tracing measured the store dataplane at 77-88% of per-request
latency (``store_transit`` share in BENCH_SERVING.json): every dispatch
and every completion paid multiple coordination-store round trips. This
module moves the DATA onto direct TCP connections and demotes the store
to what it is good at — membership and failover ground truth.

Wire format: length-prefixed pickled frames (``struct.pack(">I", n)`` +
``protocol.pack``; the store wire already trusts same-job pickles, this
is the same trust domain over a different socket). Frames are dicts with
a ``t`` tag:

    hello     {"t","peer","name"}            connection identification
    dispatch  {"t","reqs":[rec,...]}         batched request records; each
                                             rec carries its engine-stream
                                             ``seq`` so the worker consumes
                                             in order and duplicates
                                             (retransmits) are skipped
    occ       {"t","occ":{...},"ts"}         occupancy beat riding the same
                                             connection (heartbeat)
    done      {"t","recs":[...]}             completed token streams; ALWAYS
                                             written to the store first
                                             (done-before-ack invariant)
    stream    {"t","updates":[(rid,n)],"ts"} incremental token counts
    relay     {"t","rids":[...]}             prefill->router: KV pages of
                                             these rids were handed to their
                                             decode engine
    kv        {"t","rid","rec",...}          prefill->decode KV-page stream
                                             (``encode_kv``/``decode_kv``)
    tq        {"t","ch","seq","x",...}       generic tensor-queue frame: one
                                             tensor on named channel ``ch``
                                             (MPMD inter-stage activations/
                                             cotangents ride these)
    tq_ack    {"t","ch","seq"}               receiver consumed everything on
                                             ``ch`` up to and incl. ``seq``
                                             (sender drops its replay copy)
    wt        {"t","ch","seq","kind",...}    online weight-epoch stream
                                             (trainer -> engine): ``begin``
                                             opens epoch E's shadow set,
                                             ``leaf`` carries one named
                                             weight leaf (encode_tensor,
                                             bf16 wire by default), ``swap``
                                             orders the pointer-swap promote,
                                             ``discard`` drops an un-promoted
                                             shadow (rollback). Seq-acked
                                             and consumed in order per
                                             channel like dispatch records
    wt_ack    {"t","ch","seq","epoch",..}    receiver consumed the wt frame
                                             with that seq; a swap ack's
                                             ``applied`` reports whether the
                                             promote actually flipped (False
                                             = already at/past that epoch,
                                             the exactly-once no-op)
    tele      {"t","pays":[...]}             live-telemetry batches riding the
                                             occupancy beat: each payload is
                                             (src, seq)-numbered and re-sent
                                             for a few beats, so the router's
                                             aggregator dedups duplicates and
                                             a chaos-dropped frame is healed
                                             by the next beat (advisory plane:
                                             loss never blocks the request
                                             path — see observability/live.py)

Seq namespaces are PER CHANNEL, not per connection. Dispatch records
and tensor-queue frames interleave on one socket, each stream numbering
its own frames from 0 — a shared per-connection counter would make the
receiver's dedup cursor treat channel B's seq 0 as a stale duplicate of
channel A's. ``SeqChannels`` keeps one send counter and one in-order
dedup cursor per channel name; the worker's dispatch stream is channel
``"dispatch"``, MPMD boundaries use ``act<i>``/``cot<i>``.

Failure model: frames are best-effort; a lost ``dispatch`` is retransmitted
by the router when the worker's acked_seq stalls (idempotent — workers skip
seqs already consumed), a lost ``done``/``occ`` is recovered from the store
ground truth, and a lost ``kv`` falls back to router failover (re-dispatch
reruns the prefill bit-equal, seeds are explicit). Reconnects use jittered
exponential backoff so a restarted worker is not dialed in lockstep.

Every socket send/recv sits under ``protocol.deadline_guard`` —
``scripts/check_robustness.py`` rule 5 enforces it statically, the same
discipline rule 4 applies to store ops. Chaos (PADDLE_CHAOS_NET_MODE)
injects drop/half_open/latency faults at the send fences.

This module is the single writer of the ``serving_transport_*`` metric
family (scripts/check_observability.py enforces that).
"""
from __future__ import annotations

import select
import socket
import struct
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import observability as _obs
from ..testing import chaos
from .protocol import deadline_guard, pack, unpack

__all__ = [
    "TransportServer", "TransportClient", "FrameDecoder", "SeqChannels",
    "encode_frame", "encode_kv", "decode_kv",
    "encode_tensor", "decode_tensor",
    "encode_tq_frame", "decode_tq_frame", "encode_tq_ack",
    "encode_wt_frame", "decode_wt_frame", "encode_wt_ack",
]

_HDR = struct.Struct(">I")

#: per-process frame-send counter, the chaos net_fence index — a soak can
#: target "the Nth frame this process sends" deterministically
_send_index = 0

#: jittered-backoff bounds for client redials (seconds)
_BACKOFF_MIN = 0.05
_BACKOFF_MAX = 2.0

#: blocking-op timeout: sends and dials must fail fast, the deadline
#: guard above them is the watchdog of last resort
_IO_TIMEOUT = 5.0


def encode_frame(frame: dict) -> bytes:
    """Length-prefixed pickled frame, ready for one sendall."""
    payload = pack(frame)
    return _HDR.pack(len(payload)) + payload


class FrameDecoder:
    """Incremental decoder: feed raw bytes, get whole frames out."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[dict]:
        self._buf.extend(data)
        frames = []
        while True:
            if len(self._buf) < _HDR.size:
                return frames
            (n,) = _HDR.unpack(self._buf[:_HDR.size])
            if len(self._buf) < _HDR.size + n:
                return frames
            payload = bytes(self._buf[_HDR.size:_HDR.size + n])
            del self._buf[:_HDR.size + n]
            frames.append(unpack(payload))


def _count(direction: str, kind: str, nbytes: int):
    _obs.inc("serving_transport_frames_total", dir=direction, kind=kind)
    _obs.inc("serving_transport_bytes_total", nbytes, dir=direction)


def _observe_latency(frame: dict):
    """Wire latency of heartbeat-class frames that carry a send wall
    clock (occ/stream) — the streaming dataplane's transit histogram.
    Wall-to-wall, so host clock skew shifts it like srv_net_transit."""
    ts = frame.get("ts")
    if isinstance(ts, (int, float)):
        _obs.observe("serving_transport_stream_seconds",
                     max(time.time() - float(ts), 0.0))


def _send_on(raw_sock, frame: dict, what: str) -> bool:
    """Send one frame on a connected socket; chaos net fence first.
    Returns True when the frame was delivered to the kernel (half_open
    pretends success — the silently-swallowed-frame fault). Raises
    OSError on a dead peer (and ConnectionError on a chaos drop) so the
    caller can tear down and reconnect."""
    global _send_index
    idx = _send_index
    _send_index += 1
    action = chaos.net_fence(idx)
    if action == "half_open":
        return True  # swallowed: peer never sees it, sender thinks it did
    if action == "drop":
        raise ConnectionResetError("chaos net_drop severed the connection")
    data = encode_frame(frame)
    with deadline_guard(what):
        raw_sock.sendall(data)
    _count("send", str(frame.get("t")), len(data))
    return True


def _drain_sock(raw_sock, decoder: FrameDecoder, what: str) -> Optional[List[dict]]:
    """Read everything currently available; None means the peer closed
    (or errored) and the connection must be dropped."""
    frames: List[dict] = []
    while True:
        with deadline_guard(what):
            ready, _, _ = select.select([raw_sock], [], [], 0.0)
            if not ready:
                break
            try:
                data = raw_sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                return None
        if not data:
            return None
        for fr in decoder.feed(data):
            _count("recv", str(fr.get("t")), 0)
            _observe_latency(fr)
            frames.append(fr)
    return frames


class TransportServer:
    """Worker-side listener: accepts router/peer connections, drains
    frames from all of them, and can address replies by connection id."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        listen_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listen_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        with deadline_guard("transport listen"):
            listen_sock.bind((host, port))
            listen_sock.listen(16)
        listen_sock.setblocking(False)
        self._listen_sock = listen_sock
        self._host, self._port = listen_sock.getsockname()[:2]
        self._conns: Dict[int, socket.socket] = {}
        self._decoders: Dict[int, FrameDecoder] = {}
        self._next_conn = 0

    @property
    def addr(self) -> str:
        return f"{self._host}:{self._port}"

    def conn_ids(self) -> List[int]:
        return list(self._conns)

    def _accept(self):
        while True:
            with deadline_guard("transport accept"):
                try:
                    conn_sock, _ = self._listen_sock.accept()
                except (BlockingIOError, InterruptedError):
                    return
                except OSError:
                    return
            conn_sock.settimeout(_IO_TIMEOUT)
            conn_sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            cid = self._next_conn
            self._next_conn += 1
            self._conns[cid] = conn_sock
            self._decoders[cid] = FrameDecoder()

    def poll(self) -> List[Tuple[int, dict]]:
        """Accept pending connections and drain every readable one.
        Returns (conn_id, frame) pairs in arrival order per connection."""
        self._accept()
        out: List[Tuple[int, dict]] = []
        for cid in list(self._conns):
            frames = _drain_sock(self._conns[cid], self._decoders[cid],
                                 "transport recv")
            if frames is None:
                self._drop(cid)
                continue
            out.extend((cid, fr) for fr in frames)
        return out

    def send(self, conn_id: int, frame: dict) -> bool:
        """Best-effort send to one connection; a dead peer drops the
        connection and returns False (the router ground-truths through
        the store, so nothing is lost — only late)."""
        conn_sock = self._conns.get(conn_id)
        if conn_sock is None:
            return False
        try:
            return _send_on(conn_sock, frame, "transport send")
        except OSError:
            self._drop(conn_id)
            return False

    def _drop(self, conn_id: int):
        conn_sock = self._conns.pop(conn_id, None)
        self._decoders.pop(conn_id, None)
        if conn_sock is not None:
            try:
                conn_sock.close()
            except OSError:
                pass

    def close(self):
        for cid in list(self._conns):
            self._drop(cid)
        try:
            self._listen_sock.close()
        except OSError:
            pass


class TransportClient:
    """Dialer side (router->worker, prefill->decode): one persistent
    connection with jittered-backoff reconnect. ``send``/``poll`` never
    raise on a dead peer — they fail soft and schedule a redial, because
    liveness decisions belong to the router's beat-staleness failover,
    not to the transport."""

    def __init__(self, addr: str, seed: int = 0):
        host, port = addr.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._sock: Optional[socket.socket] = None
        self._decoder = FrameDecoder()
        # deterministic jitter stream per client (seeded by the target
        # port by default) so backoff schedules are reproducible in soaks
        import random as _random
        self._jitter = _random.Random((seed << 16) ^ self._port)
        self._backoff = _BACKOFF_MIN
        self._next_dial = 0.0
        self.reconnects = 0
        self._ever_connected = False
        self._dial()

    @property
    def addr(self) -> str:
        return f"{self._host}:{self._port}"

    def connected(self) -> bool:
        return self._sock is not None

    def _dial(self) -> bool:
        now = time.monotonic()
        if now < self._next_dial:
            return False
        try:
            with deadline_guard("transport dial"):
                dial_sock = socket.create_connection(
                    (self._host, self._port), timeout=_IO_TIMEOUT)
            dial_sock.settimeout(_IO_TIMEOUT)
            dial_sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = dial_sock
            self._decoder = FrameDecoder()
            self._backoff = _BACKOFF_MIN
            if self._ever_connected:
                self.reconnects += 1
                _obs.inc("serving_transport_reconnect_total")
            self._ever_connected = True
            return True
        except OSError:
            # jittered exponential backoff: reconnect storms from a fleet
            # of routers must not land on a restarted worker in lockstep
            delay = self._backoff * (0.5 + self._jitter.random())
            self._backoff = min(self._backoff * 2.0, _BACKOFF_MAX)
            self._next_dial = now + delay
            self._sock = None
            return False

    def _teardown(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._next_dial = 0.0  # redial immediately on the next op

    def send(self, frame: dict) -> bool:
        if self._sock is None and not self._dial():
            return False
        try:
            return _send_on(self._sock, frame, "transport send")
        except OSError:
            self._teardown()
            return False

    def poll(self) -> List[dict]:
        if self._sock is None:
            self._dial()
            return []
        frames = _drain_sock(self._sock, self._decoder, "transport recv")
        if frames is None:
            self._teardown()
            return []
        return frames

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None


# ---------------------------------------------------------------------------
# KV-page wire codec (the int8 frame slice of ROADMAP item 3)
# ---------------------------------------------------------------------------

def encode_kv(k: np.ndarray, v: np.ndarray, wire: str,
              k_scale: Optional[np.ndarray] = None,
              v_scale: Optional[np.ndarray] = None) -> dict:
    """Encode exported KV pages ``[L, n, Hkv, P, D]`` for the wire.

    ``raw`` ships the pool bytes untouched (bit-equal contract; an int8
    POOL's pages travel with their scale slabs, still bit-equal). ``int8``
    quantizes f32/bf16 pages with one absmax scale per ``[layer, page,
    head]`` (axis=(-2,-1) — the whole page row of a head shares a scale,
    matching the EQuARX-style coarse-grained wire) via the same
    ``quantize_absmax`` the dp gradient wire uses. Pages already int8
    (int8 pool) pass through raw — re-quantizing quantized bytes only
    loses bits.
    """
    if wire not in ("raw", "int8"):
        raise ValueError(f"kv wire must be raw|int8, got {wire!r}")
    if wire == "int8" and k.dtype != np.int8:
        from ..distributed.grad_comm import quantize_absmax

        qk, sk = quantize_absmax(k, axis=(-2, -1))
        qv, sv = quantize_absmax(v, axis=(-2, -1))
        return {"wire": "int8", "dtype": str(k.dtype),
                "k": np.asarray(qk, np.int8), "v": np.asarray(qv, np.int8),
                "k_scale": np.asarray(sk, np.float32),
                "v_scale": np.asarray(sv, np.float32)}
    payload = {"wire": "raw", "dtype": str(k.dtype),
               "k": np.asarray(k), "v": np.asarray(v)}
    if k_scale is not None:
        payload["k_scale"] = np.asarray(k_scale, np.float32)
        payload["v_scale"] = np.asarray(v_scale, np.float32)
    return payload


def decode_kv(payload: dict) -> dict:
    """Inverse of ``encode_kv``: raw passes through bit-identical;
    int8-wire dequantizes back to the export dtype. Returns
    ``{"k", "v"}`` (+ pool scale slabs for raw int8-pool pages)."""
    if payload["wire"] == "int8":
        from ..distributed.grad_comm import dequantize_absmax

        k = np.asarray(dequantize_absmax(payload["k"], payload["k_scale"]))
        v = np.asarray(dequantize_absmax(payload["v"], payload["v_scale"]))
        return {"k": k, "v": v}
    out = {"k": payload["k"], "v": payload["v"]}
    if "k_scale" in payload:
        out["k_scale"] = payload["k_scale"]
        out["v_scale"] = payload["v_scale"]
    return out


# ---------------------------------------------------------------------------
# Per-channel seq namespaces (shared by dispatch + tensor-queue streams)
# ---------------------------------------------------------------------------

class SeqChannels:
    """Per-channel seq namespaces for one frame stream.

    One instance serves both directions of a connection: ``next_seq(ch)``
    numbers outgoing frames per channel, ``stash(ch, seq, item)`` dedups
    incoming ones against a per-channel in-order cursor, and
    ``pop_next(ch)`` consumes in seq order. Channels never see each
    other's counters, so interleaved streams (dispatch records next to
    tensor-queue frames) cannot false-dedup — the bug a single
    per-connection namespace bakes in.
    """

    def __init__(self):
        self._next_send: Dict[str, int] = {}
        self._cursor: Dict[str, int] = {}
        self._stash: Dict[str, Dict[int, object]] = {}

    # -- sender side --------------------------------------------------------
    def next_seq(self, channel: str) -> int:
        n = self._next_send.get(channel, 0)
        self._next_send[channel] = n + 1
        return n

    # -- receiver side ------------------------------------------------------
    def cursor(self, channel: str) -> int:
        """Next seq this side will consume on ``channel`` — doubles as the
        ack watermark (everything below it has been consumed)."""
        return self._cursor.get(channel, 0)

    def seek(self, channel: str, seq: int):
        """Fast-forward the consume cursor (checkpoint restore: replay
        starts at the last acked microbatch, not at zero)."""
        self._cursor[channel] = int(seq)
        stash = self._stash.get(channel)
        if stash:
            for s in [s for s in stash if s < seq]:
                del stash[s]

    def stash(self, channel: str, seq: int, item) -> bool:
        """Admit an incoming item; False = duplicate (retransmit of an
        already-consumed or already-stashed seq on THIS channel)."""
        seq = int(seq)
        if seq < self.cursor(channel):
            return False
        stash = self._stash.setdefault(channel, {})
        if seq in stash:
            return False
        stash[seq] = item
        return True

    def pop_next(self, channel: str):
        """In-order consume: the item at the cursor, advancing it — or
        None when the next seq has not arrived yet."""
        stash = self._stash.get(channel)
        if not stash:
            return None
        cur = self.cursor(channel)
        if cur not in stash:
            return None
        self._cursor[channel] = cur + 1
        return stash.pop(cur)

    def advance(self, channel: str):
        """Advance the cursor past an item consumed out-of-band (the
        worker's store-mirror fallback delivers the same stream through
        the store when a socket frame was lost)."""
        self._cursor[channel] = self.cursor(channel) + 1

    def drop(self, channel: str):
        """Forget a channel entirely — stash, cursor, and send counter.
        For per-connection channels (``wt:<cid>``) whose peer died: the
        stashed items can never be consumed (their publisher's unacked
        frames die with it) and a reconnect is a NEW cid, so keeping the
        namespace only leaks memory."""
        self._stash.pop(channel, None)
        self._cursor.pop(channel, None)
        self._next_send.pop(channel, None)

    def pending(self, channel: str) -> int:
        return len(self._stash.get(channel, ()))


# ---------------------------------------------------------------------------
# Generic tensor-queue frames (MPMD inter-stage activation/cotangent wire)
# ---------------------------------------------------------------------------

#: tensor wire formats: ``raw`` ships dtype bytes untouched (bit-equal),
#: ``bf16`` halves f32 payloads, ``int8`` absmax-quantizes like the dp
#: gradient wire. Resolution mirrors grad_comm/mp_comm wire grammar.
TENSOR_WIRES = ("raw", "f32", "bf16", "int8")


def encode_tensor(arr: np.ndarray, wire: str = "raw") -> dict:
    """One tensor as a wire payload. ``raw``/``f32`` are bit-equal for
    f32 inputs (the MPMD trajectory-equality contract rides on that);
    ``bf16`` round-trips through jnp.bfloat16; ``int8`` carries one
    absmax scale per trailing row (axis=-1), matching the gradient
    wire's granularity."""
    if wire not in TENSOR_WIRES:
        raise ValueError(f"tensor wire must be one of {TENSOR_WIRES}, "
                         f"got {wire!r}")
    arr = np.asarray(arr)
    if wire == "int8" and arr.dtype != np.int8:
        from ..distributed.grad_comm import quantize_absmax

        q, scale = quantize_absmax(arr, axis=-1)
        return {"wire": "int8", "dtype": str(arr.dtype),
                "x": np.asarray(q, np.int8),
                "scale": np.asarray(scale, np.float32)}
    if wire == "bf16" and arr.dtype == np.float32:
        import jax.numpy as jnp

        return {"wire": "bf16", "dtype": str(arr.dtype),
                "x": np.asarray(jnp.asarray(arr, jnp.bfloat16))}
    return {"wire": "raw", "dtype": str(arr.dtype), "x": arr}


def decode_tensor(payload: dict) -> np.ndarray:
    """Inverse of ``encode_tensor``: back to the source dtype."""
    wire = payload["wire"]
    if wire == "int8":
        from ..distributed.grad_comm import dequantize_absmax

        out = np.asarray(dequantize_absmax(payload["x"], payload["scale"]))
        return out.astype(payload["dtype"])
    if wire == "bf16":
        return np.asarray(payload["x"]).astype(payload["dtype"])
    return payload["x"]


def encode_tq_frame(channel: str, seq: int, arr: np.ndarray,
                    wire: str = "raw", meta: Optional[dict] = None) -> dict:
    """Tensor-queue frame: channel-scoped seq + encoded tensor. ``meta``
    carries small scheduling facts (microbatch index, step) the receiver
    needs without decoding the payload."""
    frame = {"t": "tq", "ch": channel, "seq": int(seq),
             "x": encode_tensor(arr, wire)}
    if meta:
        frame["meta"] = meta
    return frame


def decode_tq_frame(frame: dict) -> Tuple[str, int, np.ndarray, dict]:
    return (frame["ch"], int(frame["seq"]), decode_tensor(frame["x"]),
            frame.get("meta") or {})


def encode_tq_ack(channel: str, seq: int) -> dict:
    """Cumulative ack: everything on ``channel`` up to and including
    ``seq`` was consumed — the sender may drop its replay copies."""
    return {"t": "tq_ack", "ch": channel, "seq": int(seq)}


# ---------------------------------------------------------------------------
# Online weight-epoch frames (serving/online.py trainer -> engine wire)
# ---------------------------------------------------------------------------

#: wt frame kinds, in protocol order: ``begin`` opens the shadow set,
#: ``leaf`` frames stream the delta, ``swap`` promotes (commit side),
#: ``discard`` drops the shadow (rollback side)
WT_KINDS = ("begin", "leaf", "swap", "discard")


def encode_wt_frame(channel: str, seq: int, kind: str, epoch: int,
                    name: Optional[str] = None, arr=None,
                    wire: str = "bf16",
                    meta: Optional[dict] = None) -> dict:
    """One weight-stream frame. ``leaf`` frames carry the named tensor
    through ``encode_tensor`` (bf16 wire by default — the PR 13 absmax
    machinery handles int8); control kinds (begin/swap/discard) carry
    only the epoch. ``meta`` rides small facts the receiver wants
    without decoding the payload (leaf count, restore spec)."""
    if kind not in WT_KINDS:
        raise ValueError(f"wt kind must be one of {WT_KINDS}, got {kind!r}")
    frame = {"t": "wt", "ch": channel, "seq": int(seq), "kind": kind,
             "epoch": int(epoch)}
    if kind == "leaf":
        if name is None or arr is None:
            raise ValueError("wt leaf frames need name and arr")
        frame["name"] = str(name)
        frame["x"] = encode_tensor(np.asarray(arr), wire)
    if meta:
        frame["meta"] = meta
    return frame


def decode_wt_frame(frame: dict):
    """-> (kind, epoch, name, arr, meta); name/arr are None for control
    kinds."""
    kind = frame["kind"]
    arr = decode_tensor(frame["x"]) if kind == "leaf" else None
    return (kind, int(frame["epoch"]), frame.get("name"), arr,
            frame.get("meta") or {})


def encode_wt_ack(channel: str, seq: int, epoch: int,
                  applied: Optional[bool] = None,
                  kind: Optional[str] = None,
                  live: Optional[int] = None) -> dict:
    """Per-frame ack (NOT cumulative — the publisher journals stream
    progress fence by fence): the wt frame with ``seq`` was consumed.

    ``kind`` echoes the acked frame's kind so the publisher can tell a
    swap ack from a begin/leaf/discard ack. ``applied`` semantics are
    per kind:

    * ``begin``   True = shadow opened; False = epoch not newer than
      live (replay of a committed epoch)
    * ``leaf``    True = staged into the open shadow; False = dropped
      (no matching shadow — replay, or rolled back)
    * ``swap``    True = the promote flipped the epoch; False = the
      exactly-once no-op (engine at/past the epoch, or no shadow)
    * ``discard`` True = a shadow was dropped; False = nothing open

    Only ``live`` — the engine's serving epoch AFTER the frame was
    applied — proves what the engine serves: a begin/leaf/discard ack
    carries the pre-flip epoch there, so no ack kind can claim a flip
    that has not happened (see OnlineCoordinator._wait_acks)."""
    ack = {"t": "wt_ack", "ch": channel, "seq": int(seq),
           "epoch": int(epoch)}
    if applied is not None:
        ack["applied"] = bool(applied)
    if kind is not None:
        ack["kind"] = str(kind)
    if live is not None:
        ack["live"] = int(live)
    return ack
