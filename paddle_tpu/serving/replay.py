"""Deterministic workload replay for the serving control plane.

The bench that proves the federated router tier scales cannot lean on
wall-clock load generators: arrival jitter would make every run a new
workload, and paying real decode cost caps a run at thousands of
requests. This module replays MILLIONS of synthetic requests through
the REAL control plane — the real ``FrontierRouter`` quota/hashing
path, the real ``Router`` admission/placement/harvest hot loop, the
real store key schema (serving/protocol.py) — against either real
engine workers or in-process **stub workers** that model service as a
fluid token rate, so the tier's own dispatch throughput is what gets
measured.

Three pillars:

- **Deterministic arrivals.** ``arrivals(spec)`` yields an endless
  time-ordered event stream (arrival time, tenant, SLO class, prompt,
  decode budget) from ``numpy.random.default_rng`` seeded per mix
  component — same spec, same seed, same stream, on any host. The mix
  grammar (docs/REPLAY.md) composes ``steady`` Poisson floors,
  ``diurnal`` sinusoid-modulated bursts, ``agentic`` multi-turn
  sessions whose prompts grow a shared prefix (high affinity reuse),
  ``longdoc`` prefill-heavy outliers, and ``abuse`` — one tenant
  flooding at a configured rate and window.

- **Virtual time.** The driver advances a ``VirtualClock`` injected
  into the frontier, every leaf router, and every stub worker, so
  deadline sheds, quota refills, liveness grace, and service completion
  are pure functions of the workload. Two replays of one seed produce
  bit-identical admission/shed/completion ledgers, fingerprinted by a
  running sha256 over every resolution (``ReplayLedger.digest``).

- **Leaf-stub mode.** ``StubWorker`` registers through the store
  exactly like an ``EngineWorker`` (count key, registration record,
  occupancy beats with monotone ``beat``/``acked_seq``/``done_count``)
  and serves the seq stream at ``tokens_per_s``, writing done keys the
  router harvests — the full store dataplane contract with zero decode
  cost, ~tens of microseconds per request end to end. ``MemStore``
  keeps the store in-process; consumed dispatch records and harvested
  done keys are reaped so a million-request run stays memory-bounded.

For wall-clock *scaling* runs (scripts/bench_replay.py) each leaf runs
in its own OS process: ``run_leaf_shard`` replays the SAME seeded
stream, keeps only the events rendezvous hashing assigns to its leaf
(the exact sticky mapping the frontier applies), and stamps the same
gid-derived seeds — so N shard processes together serve precisely the
workload one leaf serves alone, and aggregate dispatched-requests/s is
comparable. ``python -m paddle_tpu.serving.replay`` is the shard entry
point.
"""
from __future__ import annotations

import hashlib
import json
import sys
import time
from collections import deque
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..observability import accounting as _acct
from .frontier import FrontierConfig, FrontierRouter, rendezvous_rank
from .protocol import (SLO_CLASSES, deadline_guard, k_count, k_done,
                       k_engine, k_occ, k_req, pack, unpack)
from .router import Router, RouterConfig, RouterRequest

__all__ = ["MemStore", "StubWorker", "VirtualClock", "ReplayLedger",
           "arrivals", "make_spec", "build_stub_tier", "replay",
           "run_stub_replay", "run_leaf_shard"]

#: stub vocabulary for generated prompts / result tokens
_VOCAB = 50_000


class VirtualClock:
    """The replay time source: starts at 0, advances only when the
    driver says so. Injected into frontier, leaves, and stub workers so
    every timer in the tier ticks off the same deterministic axis."""

    __slots__ = ("now",)

    def __init__(self, now: float = 0.0):
        self.now = float(now)

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> float:
        self.now += dt
        return self.now


class MemStore:
    """In-process dict with the TCPStore client surface the serving
    plane uses (set/get/add/check/wait/delete_key). Single-threaded by
    design — the replay driver interleaves router pumps and worker
    polls itself."""

    def __init__(self):
        self._d: Dict[str, object] = {}

    def set(self, key: str, value):
        self._d[key] = value

    def get(self, key: str):
        return self._d[key]

    def add(self, key: str, amount: int) -> int:
        value = int(self._d.get(key, 0)) + int(amount)
        self._d[key] = value
        return value

    def check(self, keys) -> bool:
        if isinstance(keys, (list, tuple)):
            return all(k in self._d for k in keys)
        return keys in self._d

    def wait(self, keys, timeout=None):
        if not self.check(keys):
            raise RuntimeError(f"MemStore.wait: keys absent: {keys!r}")

    def delete_key(self, key: str) -> bool:
        return self._d.pop(key, None) is not None

    def __len__(self) -> int:
        return len(self._d)


class StubWorker:
    """A fluid-rate engine stand-in on the store dataplane.

    Registers exactly like ``EngineWorker`` (serving/worker.py): bumps
    the namespace count key, writes its registration record, then per
    ``poll()`` consumes the router's seq stream, "serves" queued
    requests at ``tokens_per_s`` of virtual time, writes each finished
    request's done key BEFORE the occupancy beat that acks it (the
    store-ordering contract failover depends on), and publishes a beat
    with the fields the router's liveness/harvest logic reads. Consumed
    dispatch keys are deleted so the store stays bounded."""

    def __init__(self, store, namespace: str, *, clock: VirtualClock,
                 name: Optional[str] = None, num_slots: int = 64,
                 tokens_per_s: float = 250_000.0, page_size: int = 16,
                 max_length: int = 4096):
        self._store = store
        self._ns = namespace
        self._clock = clock
        self.tokens_per_s = float(tokens_per_s)
        with deadline_guard("register engine"):
            self.index = int(store.add(k_count(namespace), 1)) - 1
        self.name = name or f"stub{self.index}"
        record = {"name": self.name, "index": self.index,
                  "num_slots": num_slots, "max_length": max_length,
                  "page_size": page_size, "buckets": [max_length],
                  "pid": 0, "addr": None, "role": "unified",
                  "kv_wire": "raw"}
        with deadline_guard("register engine"):
            store.set(k_engine(namespace, self.index), pack(record))
        self._next_seq = 0
        self._beat = 0
        self._done_count = 0
        self._budget = 0.0
        self._t = clock()
        self._q: deque = deque()  # (rid, cost, params) FIFO service line
        self._outstanding = 0

    @staticmethod
    def _result_tokens(params: dict) -> List[int]:
        """Deterministic pseudo-decode: a short stream derived from the
        request's (router/frontier-assigned) sampling seed, so identical
        placements yield identical tokens on any stub."""
        seed = int(params.get("seed") or 0)
        n = min(4, int(params.get("max_new_tokens", 1)))
        return [(seed * 7919 + i * 104729) % _VOCAB for i in range(n)]

    def poll(self) -> int:
        """One worker turn: drain dispatches, serve by rate, publish.
        Returns how many requests finished this turn."""
        now = self._clock()
        finished = 0
        with deadline_guard("stub worker pump"):
            while True:
                key = k_req(self._ns, self.name, self._next_seq)
                if not self._store.check(key):
                    break
                rec = unpack(self._store.get(key))
                self._store.delete_key(key)
                self._next_seq += 1
                cost = len(rec["prompt"]) + int(
                    rec["params"].get("max_new_tokens", 1))
                self._q.append((rec["rid"], cost, rec["params"]))
                self._outstanding += cost
            if now > self._t:
                # fluid server: capacity accrues with virtual time, capped
                # at one second of rate so idle gaps don't bank a mega-burst
                self._budget = min(self._budget
                                   + (now - self._t) * self.tokens_per_s,
                                   self.tokens_per_s)
                self._t = now
            while self._q and self._q[0][1] <= self._budget:
                rid, cost, params = self._q.popleft()
                self._budget -= cost
                self._outstanding -= cost
                self._store.set(
                    k_done(self._ns, rid),
                    pack({"rid": rid,
                          "tokens": self._result_tokens(params)}))
                self._done_count += 1
                finished += 1
            self._beat += 1
            self._store.set(k_occ(self._ns, self.name), pack({
                "beat": self._beat, "acked_seq": self._next_seq,
                "done_count": self._done_count, "name": self.name,
                "role": "unified", "prefill_queue": 0, "draining": False,
                "drained": False,
                "outstanding_tokens": int(self._outstanding)}))
        return finished


# -- workload grammar --------------------------------------------------------

def make_spec(profile: str = "mixed", seed: int = 0,
              rate_rps: float = 20_000.0, abuse_rps: float = 0.0,
              abuse_tenant: str = "abuser", tenants: int = 24,
              zipf_s: float = 1.2, tagged_share: float = 0.8) -> dict:
    """Canonical specs for the named profiles (docs/REPLAY.md).
    ``rate_rps`` is the total virtual arrival rate across the mix;
    ``abuse_rps`` > 0 adds a flooding tenant on top of it."""
    if profile == "steady":
        mix = [{"kind": "steady", "share": 1.0}]
    elif profile == "mixed":
        mix = [
            {"kind": "steady", "share": 0.35},
            {"kind": "diurnal", "share": 0.30, "amp": 0.6,
             "period_s": 20.0},
            {"kind": "agentic", "share": 0.25, "turns": 6,
             "think_s": 0.5, "turn_tokens": 12},
            {"kind": "longdoc", "share": 0.10, "doc_tokens": 384},
        ]
    else:
        raise ValueError(f"unknown profile {profile!r}")
    spec = {"seed": int(seed), "rate_rps": float(rate_rps), "mix": mix,
            "tenants": {"n": int(tenants), "zipf_s": float(zipf_s),
                        "tagged_share": float(tagged_share)},
            "slo_mix": {"interactive": 0.5, "standard": 0.35,
                        "batch": 0.15},
            "prompt_tokens": [8, 48], "max_new_tokens": [8, 32]}
    if abuse_rps > 0:
        spec["abuse"] = {"tenant": abuse_tenant, "rate_rps": float(
            abuse_rps), "start_s": 2.0, "prompt_tokens": 32,
            "max_new_tokens": 32, "slo": "interactive"}
    return spec


class _TenantPicker:
    """Zipf-ranked tenant draw: rank-i tenant has weight (i+1)^-s; a
    ``1 - tagged_share`` slice of traffic stays untagged (None). The
    CDF is precomputed — one uniform + one searchsorted per draw, not a
    weighted choice() (this runs a million times per bench)."""

    def __init__(self, cfg: dict, rng):
        n = int(cfg.get("n", 16))
        s = float(cfg.get("zipf_s", 1.2))
        w = np.arange(1, n + 1, dtype=np.float64) ** -s
        self._cdf = np.cumsum(w / w.sum())
        self._names = [f"t{i:03d}" for i in range(n)]
        self._tagged = float(cfg.get("tagged_share", 0.8))
        self._rng = rng

    def pick(self) -> Optional[str]:
        if self._rng.random() >= self._tagged:
            return None
        return self._names[int(np.searchsorted(self._cdf,
                                               self._rng.random()))]


class _SloPicker:
    """Weighted SLO-class draw off a precomputed CDF (sorted class
    order, so the draw sequence is spec-deterministic)."""

    def __init__(self, slo_mix: dict, rng):
        self._classes = sorted(slo_mix)
        w = np.asarray([slo_mix[c] for c in self._classes],
                       dtype=np.float64)
        self._cdf = np.cumsum(w / w.sum())
        self._rng = rng

    def pick(self) -> str:
        return self._classes[int(np.searchsorted(self._cdf,
                                                 self._rng.random()))]


def arrivals(spec: dict) -> Iterator[dict]:
    """Endless time-ordered event stream for ``spec``. Each event:
    ``{"t", "tenant", "slo", "prompt", "max_new_tokens"}``. Every mix
    component owns an independent, component-index-seeded generator, so
    the merged stream is deterministic no matter how far it is drawn.
    """
    seed = int(spec.get("seed", 0))
    total_rate = float(spec.get("rate_rps", 1000.0))
    lo_p, hi_p = spec.get("prompt_tokens", [8, 48])
    lo_m, hi_m = spec.get("max_new_tokens", [8, 32])
    slo_mix = spec.get("slo_mix", {"standard": 1.0})
    tcfg = spec.get("tenants", {})

    def steady_like(comp: dict, idx: int) -> Iterator[dict]:
        rng = np.random.default_rng([seed, idx])
        picker = _TenantPicker(tcfg, rng)
        slos = _SloPicker(slo_mix, rng)
        rate = total_rate * float(comp.get("share", 1.0))
        kind = comp["kind"]
        amp = float(comp.get("amp", 0.0))
        period = float(comp.get("period_s", 20.0))
        doc = int(comp.get("doc_tokens", 384))
        peak = rate * (1.0 + abs(amp)) if kind == "diurnal" else rate
        t = 0.0
        while True:
            t += rng.exponential(1.0 / peak)
            if kind == "diurnal":
                # thinning: accept against the sinusoid-modulated rate
                inst = rate * (1.0 + amp * np.sin(
                    2.0 * np.pi * t / period))
                if rng.random() * peak > max(inst, 0.0):
                    continue
            if kind == "longdoc":
                plen = int(rng.integers(doc // 2, doc + 1))
                mnew = int(rng.integers(4, 12))
            else:
                plen = int(rng.integers(lo_p, hi_p + 1))
                mnew = int(rng.integers(lo_m, hi_m + 1))
            yield {"t": t, "tenant": picker.pick(),
                   "slo": slos.pick(),
                   "prompt": rng.integers(0, _VOCAB, size=plen,
                                          dtype=np.int64),
                   "max_new_tokens": mnew}

    def agentic(comp: dict, idx: int) -> Iterator[dict]:
        """Multi-turn sessions: each turn's prompt is the session's
        growing prefix plus fresh tokens — the affinity-cache traffic
        shape. Session starts are Poisson; turns trail by think time."""
        rng = np.random.default_rng([seed, idx])
        picker = _TenantPicker(tcfg, rng)
        rate = total_rate * float(comp.get("share", 1.0))
        turns_max = int(comp.get("turns", 6))
        think = float(comp.get("think_s", 0.5))
        per_turn = int(comp.get("turn_tokens", 12))
        import heapq as _hq
        t = rng.exponential(1.0 / rate)
        pend: list = []  # (turn_t, tiebreak, remaining, prefix, tenant)
        tie = 0
        while True:
            while pend and pend[0][0] <= t:
                turn_t, _, remaining, prefix, tenant = _hq.heappop(pend)
                prompt = np.concatenate(
                    [prefix, rng.integers(0, _VOCAB, size=per_turn,
                                          dtype=np.int64)])
                mnew = int(rng.integers(lo_m, hi_m + 1))
                yield {"t": turn_t, "tenant": tenant,
                       "slo": "interactive", "prompt": prompt,
                       "max_new_tokens": mnew}
                if remaining > 1:
                    tie += 1
                    _hq.heappush(pend, (
                        turn_t + rng.exponential(think), tie,
                        remaining - 1, prompt, tenant))
            tie += 1
            _hq.heappush(pend, (
                t, tie, int(rng.integers(2, turns_max + 1)),
                rng.integers(0, _VOCAB, size=per_turn, dtype=np.int64),
                picker.pick()))
            t += rng.exponential(1.0 / rate)

    def abuse(comp: dict, idx: int) -> Iterator[dict]:
        rng = np.random.default_rng([seed, idx])
        rate = float(comp["rate_rps"])
        t = float(comp.get("start_s", 0.0))
        plen = int(comp.get("prompt_tokens", 32))
        mnew = int(comp.get("max_new_tokens", 32))
        stop = float(comp.get("end_s", float("inf")))
        while t < stop:
            t += rng.exponential(1.0 / rate)
            yield {"t": t, "tenant": comp.get("tenant", "abuser"),
                   "slo": comp.get("slo", "interactive"),
                   "prompt": rng.integers(0, _VOCAB, size=plen,
                                          dtype=np.int64),
                   "max_new_tokens": mnew}

    streams: List[Iterator[dict]] = []
    for i, comp in enumerate(spec.get("mix", [])):
        if comp["kind"] == "agentic":
            streams.append(agentic(comp, i))
        else:
            streams.append(steady_like(comp, i))
    if spec.get("abuse"):
        streams.append(abuse(spec["abuse"], len(streams)))

    import heapq as _hq
    heads = []
    for i, it in enumerate(streams):
        ev = next(it, None)
        if ev is not None:
            heads.append((ev["t"], i, ev))
    _hq.heapify(heads)
    while heads:
        t, i, ev = _hq.heappop(heads)
        yield ev
        nxt = next(streams[i], None)
        if nxt is not None:  # finite components (abuse windows) drain out
            _hq.heappush(heads, (nxt["t"], i, nxt))


# -- ledger ------------------------------------------------------------------

class _Reservoir:
    """Deterministic stride-decimated sample for quantiles: keeps every
    2^k-th value once full, so two identical runs keep identical
    samples (no RNG, no wall clock)."""

    __slots__ = ("cap", "stride", "seen", "vals")

    def __init__(self, cap: int = 65536):
        self.cap = cap
        self.stride = 1
        self.seen = 0
        self.vals: List[float] = []

    def add(self, v: float):
        if self.seen % self.stride == 0:
            if len(self.vals) >= self.cap:
                self.vals = self.vals[::2]
                self.stride *= 2
            if self.seen % self.stride == 0:
                self.vals.append(v)
        self.seen += 1

    def quantile(self, q: float) -> float:
        if not self.vals:
            return 0.0
        s = sorted(self.vals)
        return s[min(len(s) - 1, int(q * len(s)))]


class ReplayLedger:
    """Per-(tenant, slo) outcome counts, admission-latency samples, and
    the run fingerprint: a running sha256 over every resolution in
    order (gid, status, shed reason, result tokens). Same seed + same
    topology => same digest, byte for byte."""

    def __init__(self):
        self.rows: Dict[Tuple[str, str], Dict[str, int]] = {}
        self.adm_slo: Dict[str, _Reservoir] = {}
        self.adm_tenant: Dict[str, _Reservoir] = {}
        self.resolved = 0
        self._h = hashlib.sha256()

    def resolve(self, gid: int, req: RouterRequest):
        row = self.rows.setdefault((req.tenant, req.slo), {
            "done": 0, "failed": 0, "shed_quota": 0, "shed_queue_full": 0,
            "shed_deadline": 0})
        if req.status == "shed":
            row[f"shed_{req.shed_reason}"] = row.get(
                f"shed_{req.shed_reason}", 0) + 1
        else:
            row[req.status] = row.get(req.status, 0) + 1
        toks = b""
        if req.status == "done" and req.tokens is not None:
            toks = np.asarray(req.tokens, dtype=np.int64).tobytes()
        self._h.update(b"%d|%s|%s|" % (gid, req.status.encode(),
                                       (req.shed_reason or "").encode()))
        self._h.update(toks)
        if req.dispatch_t is not None:
            adm = req.dispatch_t - req.submit_t
            self.adm_slo.setdefault(req.slo, _Reservoir()).add(adm)
            self.adm_tenant.setdefault(req.tenant, _Reservoir()).add(adm)
        self.resolved += 1

    @property
    def digest(self) -> str:
        return self._h.hexdigest()

    def summary(self) -> dict:
        by_class: Dict[str, dict] = {}
        for (tenant, slo), row in self.rows.items():
            agg = by_class.setdefault(slo, {})
            for k, v in row.items():
                agg[k] = agg.get(k, 0) + v
        for slo, res in self.adm_slo.items():
            by_class.setdefault(slo, {})["admission_s"] = {
                "p50": res.quantile(0.50), "p95": res.quantile(0.95),
                "p99": res.quantile(0.99)}
        tenants = {}
        for (tenant, slo), row in sorted(self.rows.items()):
            cell = tenants.setdefault(tenant, {})
            for k, v in row.items():
                cell[k] = cell.get(k, 0) + v
        for tenant, res in self.adm_tenant.items():
            if tenant in tenants:
                tenants[tenant]["admission_p95_s"] = res.quantile(0.95)
        return {"resolved": self.resolved, "digest": self.digest,
                "classes": by_class, "tenants": tenants}


# -- drivers -----------------------------------------------------------------

def build_stub_tier(n_leaves: int, engines_per_leaf: int,
                    clock: VirtualClock, *, queue_limit: int = 4096,
                    tokens_per_s: float = 250_000.0, num_slots: int = 64,
                    dispatch_mode: str = "heap",
                    frontier_config: Optional[FrontierConfig] = None,
                    **frontier_overrides):
    """An in-process federated tier: ``n_leaves`` store-dataplane leaf
    routers (private MemStore each, results dropped through
    ``on_resolve``), ``engines_per_leaf`` stub workers per leaf with
    distinct names, one frontier on the shared virtual clock. Returns
    ``(frontier, workers, stores)``."""
    leaves, workers, stores = [], [], []
    for i in range(n_leaves):
        store = MemStore()
        ns = f"leaf{i}"
        leaves.append(Router(
            store, namespace=ns, dataplane="store",
            queue_limit=queue_limit, dispatch_mode=dispatch_mode,
            retain_results=False, harvest_budget=1024, clock=clock))
        for j in range(engines_per_leaf):
            workers.append(StubWorker(store, ns, clock=clock,
                                      name=f"l{i}e{j}",
                                      num_slots=num_slots,
                                      tokens_per_s=tokens_per_s))
        stores.append(store)
    frontier = FrontierRouter(leaves, config=frontier_config,
                              clock=clock, **frontier_overrides)
    return frontier, workers, stores


def _chain_reap(leaf: Router, inner, reap: list):
    """Wrap a leaf's resolution relay so every resolved rid queues its
    done key for deletion (after the frontier relay has run)."""
    ns, store = leaf.config.namespace, leaf._store

    def tap(req):
        if inner is not None:
            inner(req)
        reap.append((store, k_done(ns, req.rid)))
    return tap


def replay(tier, workers: List[StubWorker], clock: VirtualClock,
           spec: dict, n_requests: int, *, tick_s: float = 0.005,
           drain_ticks: int = 200_000,
           ledger: Optional[ReplayLedger] = None) -> dict:
    """Open-loop replay: inject ``spec``'s arrivals up to virtual now,
    pump the tier, poll the stubs, advance the clock — until
    ``n_requests`` have been submitted AND resolved. ``tier`` is a
    ``FrontierRouter`` or a bare leaf ``Router``; both expose the
    resolution tap the ledger hangs off. Returns the metrics block
    (wall seconds measure ONLY the replay loop — generation included,
    process startup excluded)."""
    led = ledger if ledger is not None else ReplayLedger()
    # reap queue: done keys become deletable only once the router has
    # resolved their rid — deleting earlier would strand inflight work
    reap: List[Tuple[object, str]] = []
    if isinstance(tier, FrontierRouter):
        tier.config.retain_results = False
        tier.on_resolve = led.resolve
        for leaf in tier._leaves.values():
            leaf.config.retain_results = False
            leaf.on_resolve = _chain_reap(leaf, leaf.on_resolve, reap)
    else:
        tier.config.retain_results = False

        def _tap(req, _ns=tier.config.namespace, _store=tier._store):
            led.resolve(req.rid, req)
            reap.append((_store, k_done(_ns, req.rid)))
        tier.on_resolve = _tap
    events = arrivals(spec)
    nxt = next(events)
    submitted = 0
    t0 = time.perf_counter()
    ticks = 0
    while led.resolved < submitted or submitted < n_requests:
        now = clock()
        while submitted < n_requests and nxt["t"] <= now:
            tier.submit(nxt["prompt"], slo=nxt["slo"],
                        tenant=nxt["tenant"],
                        max_new_tokens=nxt["max_new_tokens"])
            submitted += 1
            nxt = next(events)
        tier.pump()
        for w in workers:
            w.poll()
        clock.advance(tick_s)
        ticks += 1
        if len(reap) >= 4096:
            # reap resolved done keys so a million-request run keeps the
            # MemStores bounded (the router never re-reads a finished rid)
            with deadline_guard("reap done keys"):
                for store, key in reap:
                    store.delete_key(key)
            reap.clear()
        if submitted >= n_requests and ticks > drain_ticks:
            break  # safety valve: never loop forever on a stuck tier
    wall = time.perf_counter() - t0
    stats = tier.stats()
    dispatched = (stats["leaves"]["dispatched"]
                  if isinstance(tier, FrontierRouter)
                  else stats["dispatched"])
    out = {"requests": submitted, "wall_s": round(wall, 3),
           "virtual_s": round(clock(), 3), "ticks": ticks,
           "throughput_rps": round(submitted / wall, 1) if wall else 0.0,
           "dispatched": dispatched,
           "dispatch_rps": round(dispatched / wall, 1) if wall else 0.0,
           **led.summary()}
    if isinstance(tier, FrontierRouter):
        out["frontier"] = dict(tier.counters)
    return out


def run_stub_replay(spec: dict, n_requests: int, *, n_leaves: int = 1,
                    engines_per_leaf: int = 4, tick_s: float = 0.005,
                    dispatch_mode: str = "heap",
                    tokens_per_s: float = 250_000.0,
                    queue_limit: int = 4096,
                    **frontier_overrides) -> dict:
    """One-call stub-tier replay (bench + tests): build, run, report."""
    clock = VirtualClock()
    frontier, workers, _stores = build_stub_tier(
        n_leaves, engines_per_leaf, clock, queue_limit=queue_limit,
        tokens_per_s=tokens_per_s, dispatch_mode=dispatch_mode,
        **frontier_overrides)
    return replay(frontier, workers, clock, spec, n_requests,
                  tick_s=tick_s)


def _shard_key(tenant: Optional[str], prompt: np.ndarray,
               page_size: int = 16):
    """The frontier's hash key, reproduced for out-of-process shards:
    normalized tenant, or the first prompt page when untagged."""
    t = _acct.normalize_tenant(tenant)
    return t if t != _acct.DEFAULT_TENANT else prompt[:page_size].tobytes()


def run_leaf_shard(spec: dict, n_requests: int, leaf_names: List[str],
                   me: str, *, engines_per_leaf: int = 4,
                   tick_s: float = 0.005, queue_limit: int = 4096,
                   tokens_per_s: float = 250_000.0,
                   frontier_seed: int = 0) -> dict:
    """Replay ONE leaf's rendezvous share of the global stream, as its
    own process (scripts/bench_replay.py forks one per leaf). The full
    seeded stream is regenerated and filtered with the same hash the
    frontier uses, and each event keeps its GLOBAL gid-derived sampling
    seed — so N shards collectively replay exactly the 1-leaf workload
    and their summed dispatch rate is the federated tier's aggregate."""
    clock = VirtualClock()
    store = MemStore()
    leaf = Router(store, namespace=me, dataplane="store",
                  queue_limit=queue_limit, retain_results=False,
                  harvest_budget=1024, clock=clock)
    workers = [StubWorker(store, me, clock=clock, name=f"{me}e{j}",
                          num_slots=64, tokens_per_s=tokens_per_s)
               for j in range(engines_per_leaf)]
    led = ReplayLedger()
    reap: List[str] = []

    def _tap(req):
        led.resolve(req.rid, req)
        reap.append(k_done(me, req.rid))
    leaf.on_resolve = _tap

    def shard_events():
        """The first ``n_requests`` of the GLOBAL stream, filtered to
        the events rendezvous hashing assigns to this leaf — each with
        its global gid so the sampling seed matches the frontier's."""
        events = arrivals(spec)
        for gid in range(n_requests):
            ev = next(events)
            if rendezvous_rank(_shard_key(ev["tenant"], ev["prompt"]),
                               leaf_names, frontier_seed)[0] == me:
                yield gid, ev

    # materialize BEFORE the timer: every shard regenerates the full
    # global stream to filter it, and that serial cost would otherwise
    # dilute the dispatch-throughput scaling the bench is measuring
    gen_t0 = time.perf_counter()
    queued = list(shard_events())
    gen_s = time.perf_counter() - gen_t0
    stream = iter(queued)
    nxt = next(stream, None)
    submitted = 0
    t0 = time.perf_counter()
    while nxt is not None or led.resolved < submitted:
        now = clock()
        while nxt is not None and nxt[1]["t"] <= now:
            gid, ev = nxt
            leaf.submit(ev["prompt"], slo=ev["slo"],
                        tenant=ev["tenant"],
                        max_new_tokens=ev["max_new_tokens"],
                        seed=frontier_seed * 1_000_003 + gid)
            submitted += 1
            nxt = next(stream, None)
        leaf.pump()
        for w in workers:
            w.poll()
        clock.advance(tick_s)
        if len(reap) >= 4096:
            with deadline_guard("reap done keys"):
                for key in reap:
                    store.delete_key(key)
            reap.clear()
    wall = time.perf_counter() - t0
    stats = leaf.stats()
    return {"leaf": me, "requests": submitted,
            "wall_s": round(wall, 3), "gen_s": round(gen_s, 3),
            "dispatched": stats["dispatched"],
            "done": stats["done"], "shed": stats["shed"],
            "digest": led.digest}


def main(argv=None) -> int:
    """Shard entry point: ``python -m paddle_tpu.serving.replay --shard
    leaf0 --leaves leaf0,leaf1 --requests 500000`` prints the shard's
    metrics JSON on stdout (the only stdout this module produces)."""
    import argparse
    ap = argparse.ArgumentParser(prog="paddle_tpu.serving.replay")
    ap.add_argument("--shard", required=True)
    ap.add_argument("--leaves", required=True,
                    help="comma-separated leaf names (global topology)")
    ap.add_argument("--requests", type=int, required=True,
                    help="GLOBAL stream length the shard filters")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--profile", default="mixed")
    ap.add_argument("--rate-rps", type=float, default=20_000.0)
    ap.add_argument("--engines", type=int, default=4)
    ap.add_argument("--tokens-per-s", type=float, default=250_000.0)
    ap.add_argument("--tick-s", type=float, default=0.005)
    ap.add_argument("--tagged-share", type=float, default=0.8,
                    help="fraction of tagged traffic; 0 shards every "
                         "request by prompt page (uniform balance)")
    args = ap.parse_args(argv)
    spec = make_spec(args.profile, seed=args.seed, rate_rps=args.rate_rps,
                     tagged_share=args.tagged_share)
    out = run_leaf_shard(spec, args.requests,
                         args.leaves.split(","), args.shard,
                         engines_per_leaf=args.engines,
                         tick_s=args.tick_s,
                         tokens_per_s=args.tokens_per_s)
    print(json.dumps(out), file=sys.stdout)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
