"""Federated front tier: shard tenants across leaf routers, enforce
per-tenant quotas, and aggregate the fleet view.

One ``Router`` (serving/router.py) is a single pump loop — its dispatch
scan, store mirror, and done harvest saturate long before the engines
do. The ``FrontierRouter`` scales the control plane horizontally: it
owns a set of leaf routers (each with its own namespace and engine
fleet) and places every submission on exactly one leaf by **rendezvous
(highest-random-weight) hashing** of the tenant label. Rendezvous
hashing is sticky — a tenant keeps landing on the same leaf as leaves
join and leave, and only the tenants of a removed leaf move — which
keeps two things leaf-local by construction: the prefix-affinity cache
(an agentic tenant's multi-turn prompts re-hit the same leaf's paged
prefix caches) and the per-tenant cost ledger (docs/OBSERVABILITY.md
§11; no cross-leaf double counting). Untagged ("-") requests hash on
their first prompt page instead, so shared-prefix traffic without a
tenant label still aggregates on one leaf.

Quotas ride ABOVE the SLO shed ladder: each tenant has a token bucket
(``quota_rate_tokens_per_s`` + ``quota_burst_tokens``, with per-tenant
overrides) debited at admission by the request's token cost (prompt +
``max_new_tokens``). A request the bucket cannot cover is shed at the
FRONT TIER — attributed to the tenant's ledger row (``shed_requests``)
and announced by the ``tenant_quota_throttled`` event — and never
reaches a leaf, so a quota shed cannot burn the class error budget the
way a queue_full/deadline shed inside the leaf does. Buckets key on the
NORMALIZED tenant label (accounting.normalize_tenant), the same key the
ledger uses: a raw ``"  acme "`` or control-character label can neither
mint a second bucket nor — critically — drain the untagged "-" pool,
and vice versa.

Hot tenants are the one case where stickiness loses: a single tenant
heavy enough to saturate its home leaf should spread. The frontier
watches the heavy-hitter sketch (the same SpaceSaving rows that land in
``fleet_health.json``'s ``tenants.top`` — via the shared live
aggregator when telemetry is on, or its own submit-fed sketch when
not), and a tenant whose share exceeds ``hot_tenant_share`` fans out
over its top-``hot_tenant_spread`` rendezvous leaves, least-queued
first. The spread set is still rendezvous-ranked, so it is itself
sticky.

Determinism: the frontier stamps every request's sampling seed from its
GLOBAL id (``seed * 1_000_003 + gid``) before the leaf sees it, so the
leaf's own rid-based stamping never runs and greedy token streams are
bit-equal across topologies — the same workload replayed against one
leaf or eight yields identical tokens (tests/test_frontier.py).

Telemetry: this module is the single writer of the ``frontier_*``
family (check_observability.py). With the live plane on, the frontier
creates ONE ``LiveAggregator``, hands it to every leaf
(``Router.share_live_aggregator``) so wire telemetry and ledger deltas
keep flowing, and itself drives the tick: merged admission queues in
the supervisor-visible ``queues`` block (the supervisor keeps consuming
fleet_health.json unchanged) plus the per-leaf breakdown in the new
``frontier`` block.
"""
from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import observability as _obs
from ..observability import accounting as _acct
from ..observability import live as _live
from ..inference.engine import SamplingParams
from .protocol import SLO_CLASSES
from .router import Router, RouterRequest

__all__ = ["FrontierRouter", "FrontierConfig", "rendezvous_rank"]


def rendezvous_rank(key, leaf_names: Sequence[str],
                    seed: int = 0) -> List[str]:
    """Leaves ranked by highest-random-weight (rendezvous) hash for
    ``key`` (a tenant label or prompt-page bytes). Deterministic across
    processes and Python runs (blake2b, no PYTHONHASHSEED exposure);
    adding or removing a leaf only moves the keys that ranked it first.
    """
    if isinstance(key, str):
        key = key.encode("utf-8", "replace")
    salt = b"%d|" % seed + key + b"|"
    scored = []
    for name in leaf_names:
        h = hashlib.blake2b(salt + name.encode("utf-8", "replace"),
                            digest_size=8).digest()
        scored.append((int.from_bytes(h, "big"), name))
    scored.sort(reverse=True)
    return [name for _, name in scored]


@dataclass
class FrontierConfig:
    #: default per-tenant refill rate in tokens/second (0 = unlimited:
    #: no bucket is even created, the quota plane costs nothing)
    quota_rate_tokens_per_s: float = 0.0
    #: default bucket capacity in tokens (0 = 2s of rate)
    quota_burst_tokens: float = 0.0
    #: per-tenant (rate, burst) overrides; keys are normalized at
    #: construction so a raw label can never dodge its own quota
    tenant_quotas: Dict[str, Tuple[float, float]] = field(
        default_factory=dict)
    #: sketch share of priced usage past which a tenant is "hot" and
    #: may spread over several leaves
    hot_tenant_share: float = 0.25
    #: how many of its top rendezvous leaves a hot tenant fans out over
    hot_tenant_spread: int = 2
    #: seconds between heavy-hitter refreshes off the sketch
    rebalance_interval_s: float = 5.0
    #: base of the gid-derived sampling seeds (must match across
    #: topologies for bit-equal replays)
    seed: int = 0
    #: keep resolved request handles so ``status``/``result`` work after
    #: the fact; the replay harness turns this off (``on_resolve`` is
    #: the tap) to stay memory-bounded over millions of requests
    retain_results: bool = True


class _TokenBucket:
    """Classic token bucket in whatever clock the frontier runs on."""

    __slots__ = ("rate", "burst", "tokens", "t")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else 2.0 * float(rate)
        self.tokens = self.burst
        self.t = now

    def take(self, cost: float, now: float) -> bool:
        if now > self.t:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.t) * self.rate)
            self.t = now
        if cost <= self.tokens:
            self.tokens -= cost
            return True
        return False


class FrontierRouter:
    """Tenant-sharded front tier over a list of leaf ``Router``s.

    The leaves are constructed by the caller (each with its own
    namespace/store and — when determinism matters — the same injected
    clock as the frontier). Engine names must be distinct across leaves
    so merged gauges and the fleet view never alias.
    """

    def __init__(self, leaves: Sequence[Router],
                 config: Optional[FrontierConfig] = None,
                 clock: Optional[Callable[[], float]] = None,
                 **overrides):
        if config is None:
            config = FrontierConfig(**overrides)
        elif overrides:
            raise ValueError("pass config= or field overrides, not both")
        if not leaves:
            raise ValueError("need at least one leaf router")
        names = [leaf.config.namespace for leaf in leaves]
        if len(set(names)) != len(names):
            raise ValueError(f"leaf namespaces must be distinct: {names}")
        self.config = config
        self._clock = clock
        self._leaves: Dict[str, Router] = dict(zip(names, leaves))
        self._names = names
        #: normalized per-tenant quota table (see FrontierConfig)
        self._quota_table = {
            _acct.normalize_tenant(t): (float(r), float(b))
            for t, (r, b) in config.tenant_quotas.items()}
        self._buckets: Dict[str, _TokenBucket] = {}
        self._next_gid = 0
        #: gid -> (leaf namespace, leaf rid) for placed requests
        self._placed: Dict[int, Tuple[str, int]] = {}
        #: per-leaf rid -> gid reverse maps for the on_resolve relay
        self._gids: Dict[str, Dict[int, int]] = {n: {} for n in names}
        #: resolutions that raced the gid mapping: a leaf can shed a
        #: request synchronously INSIDE submit (queue preemption), i.e.
        #: before the rid->gid row exists — the relay parks the record
        #: here and submit() re-fires it immediately after mapping
        self._orphans: Dict[str, Dict[int, RouterRequest]] = {
            n: {} for n in names}
        #: gid -> synthetic shed record for quota sheds (they never
        #: reach a leaf, so the frontier answers status/result itself)
        self._quota_shed: Dict[int, RouterRequest] = {}
        #: tenants currently allowed to spread (refreshed off the
        #: heavy-hitter sketch at rebalance_interval_s)
        self._hot: Dict[str, float] = {}
        self._last_rebalance = -float("inf")
        #: submit-fed fallback sketch: the rebalance signal when the
        #: live plane (and its priced sketch) is off
        self._sketch = _acct.SpaceSavingSketch(capacity=64)
        self._sketch_total = 0.0
        self.counters = {"submitted": 0, "placed": 0, "quota_shed": 0,
                         "rebalances": 0, "hot_spread_placements": 0}
        #: frontier-side ledger: quota sheds attributed per tenant
        self._acct: Optional[_acct.TenantLedger] = None
        #: shared live aggregator (created lazily; see module docstring)
        self._live_agg: Optional[_live.LiveAggregator] = None
        self.on_resolve: Optional[Callable[[int, RouterRequest], None]] \
            = None
        for name, leaf in self._leaves.items():
            leaf.on_resolve = self._make_resolve_relay(name)
        _obs.set_gauge("frontier_leaves", len(names))

    # -- clock ---------------------------------------------------------------

    def _now(self) -> float:
        return self._clock() if self._clock is not None \
            else time.perf_counter()

    # -- placement -----------------------------------------------------------

    def _hash_key(self, tenant: str, prompt: np.ndarray):
        """What rendezvous-hashes: the tenant label, or — for untagged
        traffic — the first prompt page, so shared-prefix request floods
        without a tenant still pin to one leaf's prefix caches."""
        if tenant != _acct.DEFAULT_TENANT:
            return tenant
        page = self._leaves[self._names[0]].config.page_size
        return prompt[:page].tobytes()

    def _pick_leaf(self, tenant: str, prompt: np.ndarray) -> str:
        ranked = rendezvous_rank(self._hash_key(tenant, prompt),
                                 self._names, self.config.seed)
        if tenant in self._hot and len(ranked) > 1:
            spread = ranked[:max(2, self.config.hot_tenant_spread)]
            name = min(spread,
                       key=lambda n: (self._leaves[n].queue_depth(),
                                      spread.index(n)))
            if name != ranked[0]:
                self.counters["hot_spread_placements"] += 1
            return name
        return ranked[0]

    # -- quota ---------------------------------------------------------------

    def _quota_for(self, tenant: str) -> Tuple[float, float]:
        if tenant in self._quota_table:
            return self._quota_table[tenant]
        return (self.config.quota_rate_tokens_per_s,
                self.config.quota_burst_tokens)

    def _quota_admit(self, tenant: str, cost: int, now: float) -> bool:
        """Debit the tenant's bucket; True = admit. Buckets key on the
        normalized label — the regression surface of PR 19's accounting
        fix: an untagged "-" request can only ever touch the "-" bucket.
        """
        bucket = self._buckets.get(tenant)
        if bucket is None:
            rate, burst = self._quota_for(tenant)
            if rate <= 0:
                return True  # unlimited: no bucket, no cost
            bucket = self._buckets[tenant] = _TokenBucket(rate, burst, now)
        return bucket.take(cost, now)

    # -- admission -----------------------------------------------------------

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               slo: str = "standard", tenant: Optional[str] = None,
               **sampling) -> int:
        """Admit a request into the federated tier. Returns a global id
        usable with ``status``/``result`` regardless of which leaf (or
        the quota gate) handled it."""
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {slo!r}; expected one of {SLO_CLASSES}")
        if params is None:
            params = SamplingParams(**sampling)
        elif sampling:
            raise ValueError("pass params= or sampling kwargs, not both")
        if self._acct is None and _acct.enabled():
            self._acct = _acct.TenantLedger()
        tenant = _acct.normalize_tenant(tenant)
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        gid = self._next_gid
        self._next_gid += 1
        self.counters["submitted"] += 1
        _obs.inc("frontier_requests_total")
        now = self._now()
        cost = int(prompt.size) + int(params.max_new_tokens)
        self._sketch.offer(tenant, float(cost))
        self._sketch_total += float(cost)
        if not self._quota_admit(tenant, cost, now):
            return self._shed_quota(gid, tenant, slo, cost, now)
        if params.seed is None:
            # gid-derived seed, stamped HERE: the leaf sees an explicit
            # seed and never applies its own rid-based one, so token
            # streams are bit-equal across 1-leaf and N-leaf topologies
            params = replace(params, seed=self.config.seed * 1_000_003
                             + gid)
        name = self._pick_leaf(tenant, prompt)
        rid = self._leaves[name].submit(prompt, params=params, slo=slo,
                                        tenant=tenant)
        self._placed[gid] = (name, rid)
        self._gids[name][rid] = gid
        self.counters["placed"] += 1
        orphan = self._orphans[name].pop(rid, None)
        if orphan is not None:
            # the leaf resolved (shed) this rid synchronously during
            # submit, before the mapping above existed — deliver it now
            self._deliver(name, gid, orphan)
        return gid

    def _shed_quota(self, gid: int, tenant: str, slo: str, cost: int,
                    now: float) -> int:
        self.counters["quota_shed"] += 1
        rate, burst = self._quota_for(tenant)
        if self._acct is not None:
            # the TENANT wears the shed; no leaf ever saw the request,
            # so the class error budget cannot be charged for it
            self._acct.add(tenant, slo, shed_requests=1)
        _obs.inc("frontier_quota_shed_total")
        _acct.emit_quota_throttled(tenant, slo, cost, rate, burst)
        req = RouterRequest(rid=gid, prompt=np.empty(0, np.int64),
                            params=SamplingParams(), slo=slo,
                            submit_t=now, deadline_t=now, block_keys=[],
                            status="shed", tenant=tenant,
                            shed_reason="quota", finish_t=now)
        cb = self.on_resolve
        if cb is not None:
            cb(gid, req)
        if self.config.retain_results:
            self._quota_shed[gid] = req
        return gid

    def _deliver(self, name: str, gid: int, req: RouterRequest):
        cb = self.on_resolve
        if cb is not None:
            cb(gid, req)
        if not self.config.retain_results:
            self._gids[name].pop(req.rid, None)
            self._placed.pop(gid, None)

    def _make_resolve_relay(self, name: str):
        gids = self._gids[name]
        orphans = self._orphans[name]

        def relay(req: RouterRequest):
            gid = gids.get(req.rid)
            if gid is None:
                orphans[req.rid] = req  # resolved before mapping; see submit
                return
            self._deliver(name, gid, req)
        return relay

    # -- driving -------------------------------------------------------------

    def pump(self):
        """One federated round: pump every leaf, refresh the hot-tenant
        set at its cadence, then drive the shared live plane with the
        merged fleet view."""
        for leaf in self._leaves.values():
            leaf.pump()
        now = self._now()
        if now - self._last_rebalance >= self.config.rebalance_interval_s:
            self._last_rebalance = now
            self._refresh_hot_tenants()
        self._live_tick()
        _obs.set_gauge("frontier_queue_depth",
                       sum(leaf.queue_depth()
                           for leaf in self._leaves.values()))

    def _refresh_hot_tenants(self):
        """Re-derive the spread set from the heavy-hitter sketch: the
        live aggregator's priced rows when telemetry is on (the same
        rows fleet_health.json carries), else the frontier's own
        submit-fed token sketch."""
        if self._live_agg is not None:
            rows = self._live_agg.heavy_hitters(
                max(8, self.config.hot_tenant_spread))
        elif self._sketch_total > 0:
            rows = [(t, c / self._sketch_total)
                    for t, c, _ in self._sketch.topk(8)]
        else:
            rows = []
        hot = {t: share for t, share in rows
               if share >= self.config.hot_tenant_share
               and t != _acct.DEFAULT_TENANT}
        for tenant, share in hot.items():
            if tenant not in self._hot:
                self.counters["rebalances"] += 1
                _obs.inc("frontier_rebalance_total")
                _obs.event("frontier_hot_tenant_spread", tenant=tenant,
                           share=round(share, 6),
                           spread=self.config.hot_tenant_spread)
        self._hot = hot

    def note_hot_tenants(self, tenants: Sequence[str]):
        """Explicit override of the spread set (tests, replay scenarios,
        or a supervisor pushing policy): these tenants fan out starting
        with the next submission, sketch shares notwithstanding."""
        self._hot = {_acct.normalize_tenant(t): 1.0 for t in tenants}

    def _live_tick(self):
        if self._live_agg is None:
            if not _live.live_enabled():
                return
            self._live_agg = _live.LiveAggregator()
            for leaf in self._leaves.values():
                leaf.share_live_aggregator(self._live_agg)
        # supervisor-visible queues block: merged across leaves, same
        # schema a solo router writes — the SLO control loop
        # (serving/fleet.py) keeps consuming it unchanged
        admission = {c: 0 for c in SLO_CLASSES}
        outstanding: Dict[str, int] = {}
        merged_tenants: Dict[str, Dict[str, int]] = {}
        for leaf in self._leaves.values():
            for c, n in leaf.admission_depths().items():
                admission[c] += n
            for est in leaf._engines.values():
                if est.alive:
                    outstanding[est.name] = leaf._load_tokens(est)
            merged_tenants.update(leaf.tenant_outstanding())
        self._live_agg.note_queues({
            "admission": admission,
            "engine_outstanding_tokens": outstanding,
        })
        if merged_tenants:
            self._live_agg.note_tenants(None, merged_tenants)
        if self._acct is not None:
            self._live_agg.note_tenants(self._acct.collect_delta(), None)
        self._live_agg.note_frontier(self.fleet_view())
        self._live_agg.tick()

    def drain(self, timeout: Optional[float] = None,
              poll: float = 0.005) -> bool:
        """Pump until every leaf drains (done/failed/shed). True on full
        drain, False on (wall-clock) timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while any(leaf.pending() for leaf in self._leaves.values()):
            self.pump()
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(poll)
        return True

    def shutdown(self):
        for leaf in self._leaves.values():
            leaf.shutdown()

    # -- inspection ----------------------------------------------------------

    def status(self, gid: int) -> str:
        if gid in self._quota_shed:
            return "shed"
        name, rid = self._placed[gid]
        return self._leaves[name].status(rid)

    def result(self, gid: int) -> np.ndarray:
        req = self._quota_shed.get(gid)
        if req is not None:
            raise RuntimeError(
                f"request {gid} was shed (quota); tenant={req.tenant} "
                f"slo={req.slo}")
        name, rid = self._placed[gid]
        return self._leaves[name].result(rid)

    def leaf_of(self, gid: int) -> str:
        """Namespace of the leaf that owns ``gid`` (sticky-mapping
        tests); raises KeyError for quota sheds."""
        return self._placed[gid][0]

    def pending(self) -> int:
        return sum(leaf.pending() for leaf in self._leaves.values())

    def fleet_view(self) -> dict:
        """The merged per-leaf view the health doc's ``frontier`` block
        carries: queue depths and liveness per leaf, fleet admission
        totals, quota and hot-tenant state."""
        leaves = {}
        admission = {c: 0 for c in SLO_CLASSES}
        for name, leaf in self._leaves.items():
            depths = leaf.admission_depths()
            for c, n in depths.items():
                admission[c] += n
            leaves[name] = {
                "queue_depth": leaf.queue_depth(),
                "pending": leaf.pending(),
                "engines_alive": leaf._alive_count(),
                "admission": depths,
                "dispatched": leaf.counters["dispatched"],
                "shed": leaf.counters["shed"],
            }
        return {
            "leaves": leaves,
            "admission": admission,
            "queue_depth": sum(v["queue_depth"] for v in leaves.values()),
            "quota": {
                "tracked_buckets": len(self._buckets),
                "throttled_total": self.counters["quota_shed"],
            },
            "hot_tenants": sorted(self._hot),
        }

    def stats(self) -> dict:
        """Frontier counters + summed leaf counters + per-leaf stats."""
        per_leaf = {name: leaf.stats()
                    for name, leaf in self._leaves.items()}
        summed: Dict[str, int] = {}
        for st in per_leaf.values():
            for k, v in st.items():
                if isinstance(v, (int, float)):
                    summed[k] = summed.get(k, 0) + v
        return {**self.counters, "leaves": summed, "per_leaf": per_leaf}
