"""Engine worker: one DecodeEngine behind the coordination store.

Registers in the store under the serving namespace (a race-free index
from the atomic ``add`` counter), then loops: drain dispatched requests
into the engine, advance the scheduler one step (with a chaos
``engine_fence`` so soaks can SIGKILL it mid-decode), publish finished
token streams, and publish an occupancy beat. The router
(serving/router.py) never talks to the worker directly — everything
rides store keys, so a worker death is detected by beat staleness and
its unfinished work is resubmitted elsewhere.

Crash-safety ordering: a request's ``done`` key is written BEFORE the
occupancy beat that acks it, so failover can harvest everything a dead
engine finished; anything not harvested is re-run bit-equal (the router
assigns every request an explicit sampling seed — the engine's implicit
``fold_in(base_key, local_rid)`` default would differ across engines).

Run standalone (the bench and chaos soaks spawn this)::

    python -m paddle_tpu.serving.worker --master 127.0.0.1:29510 \
        --model-seed 7 --hidden 64 --layers 2 --heads 4 --vocab 128

The launch CLI can supervise it (``--serving_master`` exports
PADDLE_SERVING_MASTER and relaunch-on-death re-registers the worker as a
fresh engine; the router fails the dead one over in the meantime).
"""
from __future__ import annotations

import os
import sys
import time
from typing import Dict, Optional

import numpy as np

from .. import observability as _obs
from ..inference.engine import DecodeEngine, EngineConfig, SamplingParams
from ..testing import chaos
from .protocol import (DEFAULT_NAMESPACE, deadline_guard, k_ctl, k_done,
                       k_engine, k_occ, k_req, k_count, pack, unpack)

__all__ = ["EngineWorker", "main"]


class EngineWorker:
    """Wrap a DecodeEngine as a store-coordinated serving worker."""

    def __init__(self, model, store, config: Optional[EngineConfig] = None,
                 *, name: Optional[str] = None,
                 namespace: str = DEFAULT_NAMESPACE,
                 step_floor_s: float = 0.0, **overrides):
        self.engine = DecodeEngine(model, config, **overrides)
        self._store = store
        self._ns = namespace
        self._step_floor_s = float(step_floor_s)
        with deadline_guard("register engine"):
            self.index = int(self._store.add(k_count(namespace), 1)) - 1
        self.name = name or f"engine{self.index}"
        cfg = self.engine.config
        record = {
            "name": self.name,
            "index": self.index,
            "num_slots": cfg.num_slots,
            "max_length": cfg.max_length,
            "page_size": cfg.page_size,
            "buckets": list(self.engine.buckets),
            "pid": os.getpid(),
        }
        with deadline_guard("register engine"):
            self._store.set(k_engine(namespace, self.index), pack(record))
        self._next_seq = 0  # next request seq to consume from the store
        self._beat = 0
        self._local_rid: Dict[int, int] = {}  # engine rid -> router rid
        self._last_occ_pub = 0.0
        self._last_drain = -float("inf")
        self._done_count = 0  # lifetime results published (rides the beat)
        self.publish_occupancy()

    # -- store I/O ----------------------------------------------------------

    def _drain_requests(self):
        """Consume this engine's request stream in seq order; each record
        becomes one engine.submit with the router-assigned seed."""
        while True:
            key = k_req(self._ns, self.name, self._next_seq)
            with deadline_guard("recv request"):
                if not self._store.check(key):
                    return
                rec = unpack(self._store.get(key))
            self._next_seq += 1
            rid = rec["rid"]
            tr = rec.get("trace")
            dh = None
            if tr:
                # continue the router's trace: the transit span is wall-
                # to-wall against the router's dispatch_ts (host clock
                # skew shifts it; every other duration is monotonic)
                _obs.record_span(
                    "srv_store_transit", trace_id=tr["trace_id"],
                    parent_id=tr["parent_id"],
                    start_ts=tr.get("dispatch_ts"), rid=rid,
                    engine=self.name,
                    retry=int(tr.get("resubmits", 0) or 0) > 0)
                dh = _obs.start_span(
                    "srv_drain", trace_id=tr["trace_id"],
                    parent_id=tr["parent_id"], rid=rid, engine=self.name)
            try:
                local = self.engine.submit(
                    np.asarray(rec["prompt"], np.int64),
                    SamplingParams(**rec["params"]), trace=tr)
            except ValueError as e:
                # invalid geometry for THIS engine (bucket/page limits):
                # report instead of dying — the router surfaces the error
                if dh:
                    _obs.end_span(dh, error=str(e))
                with deadline_guard("publish result"):
                    self._store.set(k_done(self._ns, rid), pack(
                        {"rid": rid, "engine": self.name, "error": str(e)}))
                self._done_count += 1
                continue
            if dh:
                _obs.end_span(dh)
            self._local_rid[local] = rid

    def _publish_done(self) -> int:
        """Write finished token streams; returns how many. Runs BEFORE
        publish_occupancy in poll_once so a completed request is always
        harvestable once its seq is acked — the failover no-loss/no-dup
        invariant."""
        published = 0
        for local, rid in list(self._local_rid.items()):
            if self.engine._requests[local].status != "done":
                continue
            tokens = self.engine.result(local)
            with deadline_guard("publish result"):
                self._store.set(k_done(self._ns, rid), pack({
                    "rid": rid, "engine": self.name,
                    "tokens": np.asarray(tokens).tolist()}))
            del self._local_rid[local]
            self._done_count += 1
            published += 1
        return published

    def publish_occupancy(self):
        """Occupancy beat: engine load snapshot + monotone ``beat`` (the
        router's liveness signal) + ``acked_seq`` (requests consumed, so
        the router can estimate load it dispatched but the engine hasn't
        reported yet)."""
        self._beat += 1
        self._last_occ_pub = time.monotonic()
        occ = self.engine.occupancy()
        occ["beat"] = self._beat
        occ["acked_seq"] = self._next_seq
        occ["done_count"] = self._done_count
        occ["name"] = self.name
        with deadline_guard("publish occupancy"):
            self._store.set(k_occ(self._ns, self.name), pack(occ))

    def stop_requested(self) -> bool:
        ctl = k_ctl(self._ns)
        with deadline_guard("poll ctl"):
            if not self._store.check(ctl):
                return False
            rec = unpack(self._store.get(ctl))
        return bool(rec.get("stop"))

    # -- scheduler ----------------------------------------------------------

    def poll_once(self) -> bool:
        """One deterministic worker round: drain new requests, advance the
        engine one step (chaos fence first — PADDLE_CHAOS_ENGINE_* can
        SIGKILL here, mid-decode), publish results + occupancy. The
        occupancy beat is throttled to ~100 Hz: the router samples it far
        slower, and unthrottled publishes just contend the store (the
        routers' liveness grace is seconds, results ride done keys, and
        a fresh publish always follows a finished request). The request
        drain check is likewise throttled to ~50 Hz while the engine is
        busy — its internal queue keeps the slots fed between checks; an
        idle engine checks every poll so first dispatch lands fast.
        Returns True while the engine still holds work."""
        now = time.monotonic()
        if not self._local_rid or now - self._last_drain >= 0.02:
            self._last_drain = now
            self._drain_requests()
        chaos.engine_fence(self.engine.decode_steps)
        t_step = time.monotonic()
        busy = self.engine.step()
        if busy and self._step_floor_s > 0.0:
            # device-step floor: pace the scheduler as if each step were
            # accelerator-bound (host idle while the device runs). Lets
            # CPU-only hosts measure control-plane scaling, and doubles
            # as a crude per-engine rate limiter.
            rem = self._step_floor_s - (time.monotonic() - t_step)
            if rem > 0.0:
                time.sleep(rem)
        published = self._publish_done()
        if published or time.monotonic() - self._last_occ_pub >= 0.025:
            self.publish_occupancy()
        return busy or bool(self._local_rid)

    def serve(self, poll_interval: float = 0.005,
              ctl_interval: float = 0.25):
        """Poll until the router broadcasts stop. Idle rounds sleep
        ``poll_interval`` (the engine's own admission backoff bounds the
        pages-starved case); the stop broadcast is only polled every
        ``ctl_interval`` seconds — it is the cold path."""
        last_ctl = -float("inf")
        while True:
            now = time.monotonic()
            if now - last_ctl >= ctl_interval:
                last_ctl = now
                if self.stop_requested():
                    return
            if not self.poll_once():
                time.sleep(poll_interval)


def build_worker_model(args):
    """Deterministic tiny-GPT build shared by every worker process AND the
    in-process reference engines of the tests/bench: same seed => same
    weights => bit-equal token streams across processes."""
    import paddle_tpu as paddle
    from ..text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(args.model_seed)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_hidden_layers=args.layers, num_attention_heads=args.heads,
        max_position_embeddings=args.max_positions,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
    model.eval()
    return model


def build_arg_parser():
    import argparse

    p = argparse.ArgumentParser("paddle_tpu.serving.worker")
    p.add_argument("--master", default=os.environ.get(
        "PADDLE_SERVING_MASTER", "127.0.0.1:29500"),
        help="host:port of the coordination store (PADDLE_SERVING_MASTER)")
    p.add_argument("--namespace", default=DEFAULT_NAMESPACE)
    p.add_argument("--name", default=None)
    p.add_argument("--poll-interval", type=float, default=0.005)
    p.add_argument("--step-floor-ms", type=float, default=0.0,
                   help="minimum wall time per scheduler step; emulates "
                        "accelerator-bound steps on CPU-only hosts and "
                        "doubles as a crude rate limiter")
    p.add_argument("--warmup", action="store_true",
                   help="pre-compile every prefill bucket and the decode "
                        "program before serving, so placement luck cannot "
                        "land an XLA compile on the request path")
    # model spec (must match the router/bench reference build)
    p.add_argument("--model-seed", type=int, default=7)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--max-positions", type=int, default=512)
    # engine geometry
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max-length", type=int, default=256)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--speculate-k", type=int, default=0)
    p.add_argument("--no-prefix-cache", action="store_true")
    p.add_argument("--kv-dtype", default="f32")
    p.add_argument("--mp", type=int, default=1,
                   help="shard decode over this many devices' mp axis "
                        "(dp1 x mp mesh over the first mp local devices)")
    return p


def main(argv=None):
    args = build_arg_parser().parse_args(argv)
    from ..runtime import TCPStore

    model = build_worker_model(args)
    mesh = None
    if args.mp > 1:
        import jax

        from ..distributed.mesh import build_mesh

        mesh = build_mesh((1, args.mp), ("dp", "mp"),
                          devices=jax.devices()[:args.mp])
    host, port = args.master.rsplit(":", 1)
    store = TCPStore(host=host, port=int(port), is_master=False, timeout=60.0)
    worker = EngineWorker(
        model, store, name=args.name, namespace=args.namespace,
        step_floor_s=args.step_floor_ms / 1000.0,
        num_slots=args.slots, max_length=args.max_length,
        page_size=args.page_size, speculate_k=args.speculate_k,
        prefix_cache=not args.no_prefix_cache, kv_dtype=args.kv_dtype,
        mesh=mesh)
    if args.warmup:
        for b in worker.engine.buckets:
            n = max(1, min(int(b), args.max_length - 4))
            worker.engine.submit(np.full(n, 1, np.int64),
                                 SamplingParams(max_new_tokens=2))
        worker.engine.run()
        print(f"[serving] worker {worker.name} warm "
              f"({len(worker.engine.buckets)} buckets)",
              file=sys.stderr, flush=True)
    print(f"[serving] worker {worker.name} (engine {worker.index}) "
          f"serving via {args.master}", file=sys.stderr, flush=True)
    worker.serve(poll_interval=args.poll_interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())
