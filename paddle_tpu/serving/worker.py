"""Engine worker: one DecodeEngine behind the streaming dataplane.

Registers in the coordination store under the serving namespace (a
race-free index from the atomic ``add`` counter) — the registration
record carries the worker's transport listen address, its ``role``
(``unified`` | ``prefill`` | ``decode``) and its KV wire codec — then
loops: drain dispatched requests (direct ``dispatch`` frames from the
router's persistent socket, with the legacy store keys as the A/B and
socket-failure fallback), advance the scheduler one step (with a chaos
``engine_fence`` so soaks can SIGKILL it mid-decode), publish finished
token streams, and publish an occupancy beat. Occupancy rides the SAME
socket as the data (the heartbeat) and is mirrored to the store at a
slow cadence — the store stays the membership/failover ground truth,
but per-request latency no longer pays store round trips.

Roles (disaggregated prefill/decode):

* ``unified`` — classic worker: local prefill + decode per request.
* ``prefill`` — dispatch records arrive with a ``kv_to`` target; the
  worker runs ``engine.prefill_export`` and streams the finished KV
  pages (``transport.encode_kv``) straight to the target decode worker,
  then tells the router via a ``relay`` frame. It never decodes.
* ``decode`` — imports streamed KV pages (``engine.try_import_prefill``,
  bit-equal to a local prefill on the raw wire) and decodes; it also
  accepts direct dispatches (short prompts skip disaggregation).

Crash-safety ordering is UNCHANGED from the store dataplane: a request's
``done`` key is written to the STORE before the occupancy beat that acks
it — wire ``done`` frames are a latency optimization on top, not the
ground truth — so failover can harvest everything a dead engine
finished; anything not harvested is re-run bit-equal (the router assigns
every request an explicit sampling seed — the engine's implicit
``fold_in(base_key, local_rid)`` default would differ across engines).

Run standalone (the bench and chaos soaks spawn this)::

    python -m paddle_tpu.serving.worker --master 127.0.0.1:29510 \
        --model-seed 7 --hidden 64 --layers 2 --heads 4 --vocab 128

The launch CLI can supervise it (``--serving_master`` exports
PADDLE_SERVING_MASTER and relaunch-on-death re-registers the worker as a
fresh engine; the router fails the dead one over in the meantime).
"""
from __future__ import annotations

import os
import sys
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from .. import observability as _obs
from ..observability import live as _live
from ..inference.engine import DecodeEngine, EngineConfig, SamplingParams
from ..testing import chaos
from .protocol import (DEFAULT_NAMESPACE, deadline_guard, k_ctl,
                       k_ctl_engine, k_done, k_engine, k_occ, k_req,
                       k_count, pack, unpack)
from .transport import (SeqChannels, TransportClient, TransportServer,
                        decode_kv, encode_kv)

__all__ = ["EngineWorker", "main"]

ROLES = ("unified", "prefill", "decode")

#: store-mirror cadence for occupancy/fallback drains once a router
#: socket is attached (the wire is the hot path; the store is failover
#: ground truth and only needs a slow heartbeat)
_STORE_MIRROR_S = 0.25


class EngineWorker:
    """Wrap a DecodeEngine as a transport-served, store-coordinated
    serving worker."""

    def __init__(self, model, store, config: Optional[EngineConfig] = None,
                 *, name: Optional[str] = None,
                 namespace: str = DEFAULT_NAMESPACE,
                 step_floor_s: float = 0.0, role: str = "unified",
                 kv_wire: str = "raw", **overrides):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        if kv_wire not in ("raw", "int8"):
            raise ValueError(f"kv_wire must be raw|int8, got {kv_wire!r}")
        self.engine = DecodeEngine(model, config, **overrides)
        self._store = store
        self._ns = namespace
        self._step_floor_s = float(step_floor_s)
        self.role = role
        self._kv_wire = kv_wire
        self._server = TransportServer()
        with deadline_guard("register engine"):
            self.index = int(self._store.add(k_count(namespace), 1)) - 1
        self.name = name or f"engine{self.index}"
        cfg = self.engine.config
        record = {
            "name": self.name,
            "index": self.index,
            "num_slots": cfg.num_slots,
            "max_length": cfg.max_length,
            "page_size": cfg.page_size,
            "buckets": list(self.engine.buckets),
            "pid": os.getpid(),
            "addr": self._server.addr,
            "role": role,
            "kv_wire": kv_wire,
        }
        with deadline_guard("register engine"):
            self._store.set(k_engine(namespace, self.index), pack(record))
        #: per-channel seq state: the dispatch stream is one channel of a
        #: shared connection (tensor-queue frames number independently)
        self._rx_seq = SeqChannels()
        self._beat = 0
        self._local_rid: Dict[int, int] = {}  # engine rid -> router rid
        self._last_occ_pub = 0.0
        self._last_occ_store = -float("inf")
        self._last_drain = -float("inf")
        self._last_store_drain = -float("inf")
        self._done_count = 0  # lifetime results published (rides the beat)
        #: connection ids that sent a router hello (done/occ frames go here)
        self._router_cids: set = set()
        #: prefill role: dispatch records awaiting export + KV handoff
        self._prefill_jobs: deque = deque()
        #: decode/unified role: kv frames awaiting a free slot
        self._kv_imports: deque = deque()
        #: prefill role: persistent links to decode workers, by address
        self._kv_links: Dict[str, TransportClient] = {}
        #: live-telemetry shipper, created lazily on the first beat with
        #: the plane enabled (one env lookup per beat when it is off)
        self._live_shipper: Optional[_live.LiveShipper] = None
        #: fleet-supervisor drain order (per-engine ctl key): while True
        #: the worker admits NO new dispatches but keeps stepping the
        #: engine so in-flight requests finish; once idle its beat
        #: advertises ``drained`` and the supervisor can flip its role
        self.draining = False
        self._idle = True
        self._last_drain_ctl = -float("inf")
        #: connection ids with a live wt (weight-epoch) stream attached
        self._wt_cids: set = set()
        self.publish_occupancy()

    # -- transport I/O ------------------------------------------------------

    def _pump_transport(self):
        """Drain every transport connection: stash dispatch records by
        seq (the consume loop enforces order and skips duplicates from
        retransmits), queue KV-page streams, learn which connections are
        routers."""
        for cid, frame in self._server.poll():
            t = frame.get("t")
            if t == "hello":
                if frame.get("peer") == "router":
                    self._router_cids.add(cid)
            elif t == "dispatch":
                # only routers dispatch: treat the conn as one even if
                # its hello frame was lost (chaos half_open)
                self._router_cids.add(cid)
                for rec in frame.get("reqs", ()):
                    self._rx_seq.stash("dispatch", int(rec["seq"]), rec)
            elif t == "kv":
                self._kv_imports.append(frame)
            elif t == "wt":
                # weight-epoch stream (serving/online.py). Seqs are per
                # PUBLISHER CONNECTION: a restarted coordinator redials
                # and restarts at 0, so the channel is keyed by cid
                self._rx_seq.stash(f"wt:{cid}", int(frame["seq"]),
                                   (cid, frame))
                self._wt_cids.add(cid)
        live = set(self._server.conn_ids())
        self._router_cids &= live

    #: wt frames applied per poll round. A whole epoch's leaves can land
    #: in one socket batch; applying them all before the next engine
    #: step would stall in-flight decode — the drain the flip exists to
    #: avoid. Bounding the per-round apply keeps per-step jitter at a
    #: few leaf decodes while the stream spreads across poll rounds.
    _WT_FRAMES_PER_POLL = 2

    def _drain_weights(self):
        """Apply stashed wt frames in seq order between engine steps —
        ``online.apply_wt_frame`` is the sole promote/discard chokepoint
        — and ack each one back to its publisher. Runs even while
        draining: a weight flip is not an admission. The per-poll budget
        is round-robined one frame per publisher connection per pass, so
        concurrent publishers make proportional progress instead of the
        first cid in iteration order consuming the whole budget."""
        from .online import apply_wt_frame
        budget = self._WT_FRAMES_PER_POLL
        cids = sorted(self._wt_cids)
        while budget > 0:
            progressed = False
            for cid in cids:
                if budget <= 0:
                    break
                item = self._rx_seq.pop_next(f"wt:{cid}")
                if item is None:
                    continue
                _cid, frame = item
                ack = apply_wt_frame(self.engine, frame)
                self._server.send(cid, ack)
                budget -= 1
                progressed = True
            if not progressed:
                break
        # dead publishers: drop the whole per-cid channel, not just the
        # cid — stashed frames of a dead connection can never be
        # consumed and a reconnect arrives under a fresh cid
        live = set(self._server.conn_ids())
        for cid in self._wt_cids - live:
            self._rx_seq.drop(f"wt:{cid}")
        self._wt_cids &= live

    def _send_routers(self, frame: dict):
        for cid in list(self._router_cids):
            self._server.send(cid, frame)

    # -- request intake -----------------------------------------------------

    def _drain_requests(self):
        """Consume this engine's request stream in seq order. Wire-stashed
        records are consumed first (no store round trip); the store key
        for the same seq is only checked at the slow mirror cadence once
        a router socket is attached — it is the fallback path for frames
        lost to a socket failure, and the ONLY path on the legacy store
        dataplane (no router connection)."""
        while True:
            rec = self._rx_seq.pop_next("dispatch")
            src = "wire"
            if rec is None:
                now = time.monotonic()
                if (self._router_cids
                        and now - self._last_store_drain < _STORE_MIRROR_S):
                    return
                key = k_req(self._ns, self.name,
                            self._rx_seq.cursor("dispatch"))
                with deadline_guard("recv request"):
                    if not self._store.check(key):
                        self._last_store_drain = now
                        return
                    rec = unpack(self._store.get(key))
                src = "store"
                self._rx_seq.advance("dispatch")
            self._consume(rec, src)

    def _consume(self, rec: dict, src: str):
        """One dispatch record into the engine (unified/decode) or the
        prefill job queue (prefill role)."""
        rid = rec["rid"]
        tr = rec.get("trace")
        dh = None
        if tr:
            # continue the router's trace: the transit span is wall-to-
            # wall against the router's dispatch_ts (host clock skew
            # shifts it; every other duration is monotonic)
            retry = int(tr.get("resubmits", 0) or 0) > 0
            if src == "wire":
                _obs.record_span(
                    "srv_net_transit", trace_id=tr["trace_id"],
                    parent_id=tr["parent_id"],
                    start_ts=tr.get("dispatch_ts"), rid=rid,
                    engine=self.name, retry=retry)
            else:
                _obs.record_span(
                    "srv_store_transit", trace_id=tr["trace_id"],
                    parent_id=tr["parent_id"],
                    start_ts=tr.get("dispatch_ts"), rid=rid,
                    engine=self.name, retry=retry)
            dh = _obs.start_span(
                "srv_drain", trace_id=tr["trace_id"],
                parent_id=tr["parent_id"], rid=rid, engine=self.name)
        if self.role == "prefill":
            self._prefill_jobs.append({"rec": rec, "frame": None})
            if dh:
                _obs.end_span(dh, queued="prefill")
            return
        try:
            local = self.engine.submit(
                np.asarray(rec["prompt"], np.int64),
                SamplingParams(**rec["params"]), trace=tr,
                tenant=rec.get("tenant"), slo=rec.get("slo"))
        except ValueError as e:
            # invalid geometry for THIS engine (bucket/page limits):
            # report instead of dying — the router surfaces the error
            if dh:
                _obs.end_span(dh, error=str(e))
            self._publish_one_done(
                {"rid": rid, "engine": self.name, "error": str(e)})
            return
        if dh:
            _obs.end_span(dh)
        self._local_rid[local] = rid

    # -- disaggregated prefill ----------------------------------------------

    def _advance_prefill(self):
        """Prefill role: run the head job's prefill and stream its KV
        pages to the target decode worker. A job whose export cannot get
        a slot/pages stays at the head and is retried next poll; a job
        whose KV send failed rotates to the BACK so one unreachable
        decode peer cannot head-of-line-block handoffs to the others
        (the built frame is cached, so a resend never re-runs the
        prefill)."""
        for _ in range(len(self._prefill_jobs)):
            job = self._prefill_jobs[0]
            rec = job["rec"]
            rid = rec["rid"]
            if job["frame"] is None:
                tr = rec.get("trace")
                try:
                    payload = self.engine.prefill_export(
                        np.asarray(rec["prompt"], np.int64),
                        SamplingParams(**rec["params"]), trace=tr,
                        tenant=rec.get("tenant"), slo=rec.get("slo"))
                except ValueError as e:
                    self._publish_one_done(
                        {"rid": rid, "engine": self.name, "error": str(e)})
                    self._prefill_jobs.popleft()
                    continue
                if payload is None:
                    return  # no slot/pages yet; retry next poll
                if "done" in payload:
                    # finished at prefill (1-token budget / instant EOS)
                    self._publish_one_done(
                        {"rid": rid, "engine": self.name,
                         "tokens": np.asarray(payload["done"]).tolist()})
                    self._prefill_jobs.popleft()
                    continue
                job["frame"] = {
                    "t": "kv", "rid": rid, "rec": rec,
                    "first_token": payload["first_token"],
                    "true_len": payload["true_len"],
                    "prefill_s": payload["prefill_s"],
                    "kv": encode_kv(payload["k"], payload["v"],
                                    self._kv_wire, payload.get("ks"),
                                    payload.get("vs")),
                    "ts": time.time(),
                }
            link = self._kv_link(rec["kv_to"]["addr"])
            if not link.send(job["frame"]):
                # decode peer unreachable; rotate and let backoff govern
                # the redial while other targets make progress
                self._prefill_jobs.rotate(-1)
                continue
            self._prefill_jobs.popleft()
            # tell the router the handoff happened, so it can retire this
            # rid from the prefill stream's load accounting
            self._send_routers({"t": "relay", "rids": [rid]})

    def _kv_link(self, addr: str) -> TransportClient:
        link = self._kv_links.get(addr)
        if link is None:
            link = TransportClient(addr, seed=self.index)
            self._kv_links[addr] = link
        return link

    def _advance_kv_imports(self):
        """Decode/unified role: adopt streamed prefills as free slots
        allow. Import order is arrival order; a head frame waiting for a
        slot blocks the rest (they need slots too)."""
        while self._kv_imports:
            frame = self._kv_imports[0]
            rec = frame["rec"]
            rid = rec["rid"]
            tr = rec.get("trace")
            kv = frame.get("_decoded")
            if kv is None:
                got = decode_kv(frame["kv"])
                kv = {"first_token": frame["first_token"],
                      "true_len": frame["true_len"],
                      "prefill_s": frame.get("prefill_s", 0.0),
                      "k": got["k"], "v": got["v"]}
                if "k_scale" in got:  # int8 POOL slabs travel raw
                    kv["ks"] = got["k_scale"]
                    kv["vs"] = got["v_scale"]
                frame["_decoded"] = kv
            try:
                local = self.engine.try_import_prefill(
                    np.asarray(rec["prompt"], np.int64),
                    SamplingParams(**rec["params"]), kv, trace=tr,
                    tenant=rec.get("tenant"), slo=rec.get("slo"))
            except ValueError as e:
                self._publish_one_done(
                    {"rid": rid, "engine": self.name, "error": str(e)})
                self._kv_imports.popleft()
                continue
            if local is None:
                return  # no slot/pages yet; retry next poll
            if tr:
                # wall-to-wall KV stream span: export-side send stamp to
                # import completion, the disaggregated analogue of the
                # transit spans
                _obs.record_span(
                    "srv_kv_stream", trace_id=tr["trace_id"],
                    parent_id=tr["parent_id"], start_ts=frame.get("ts"),
                    rid=rid, engine=self.name,
                    wire=frame["kv"].get("wire"),
                    pages=int(np.asarray(frame["kv"]["k"]).shape[1]))
            self._local_rid[local] = rid
            self._kv_imports.popleft()

    # -- results + occupancy ------------------------------------------------

    def _publish_one_done(self, rec: dict):
        """STORE first (harvest ground truth), wire echo second — the
        done-before-ack invariant rides the store write order."""
        with deadline_guard("publish result"):
            self._store.set(k_done(self._ns, rec["rid"]), pack(rec))
        self._done_count += 1
        self._send_routers({"t": "done", "recs": [rec]})

    def _publish_done(self) -> int:
        """Write finished token streams; returns how many. Runs BEFORE
        publish_occupancy in poll_once so a completed request is always
        harvestable once its seq is acked — the failover no-loss/no-dup
        invariant. The wire echo (one batched frame) happens after every
        store write, so a router acting on the frame can already trust
        the store."""
        recs = []
        for local, rid in list(self._local_rid.items()):
            if self.engine._requests[local].status != "done":
                continue
            tokens = self.engine.result(local)
            rec = {"rid": rid, "engine": self.name,
                   "tokens": np.asarray(tokens).tolist()}
            with deadline_guard("publish result"):
                self._store.set(k_done(self._ns, rid), pack(rec))
            del self._local_rid[local]
            self._done_count += 1
            recs.append(rec)
        if recs:
            self._send_routers({"t": "done", "recs": recs})
        return len(recs)

    def publish_occupancy(self, force_store: bool = False):
        """Occupancy beat: engine load snapshot + monotone ``beat`` (the
        router's liveness signal) + ``acked_seq`` (requests consumed, so
        the router can estimate load it dispatched but the engine hasn't
        reported yet). The beat rides the router socket as the heartbeat;
        the store copy — the failover ground truth — is mirrored at a
        slow cadence (every write follows the done keys it acks)."""
        self._beat += 1
        now = time.monotonic()
        self._last_occ_pub = now
        occ = self.engine.occupancy()
        occ["beat"] = self._beat
        occ["acked_seq"] = self._rx_seq.cursor("dispatch")
        occ["done_count"] = self._done_count
        occ["name"] = self.name
        occ["role"] = self.role
        occ["prefill_queue"] = len(self._prefill_jobs)
        occ["draining"] = self.draining
        occ["drained"] = self.draining and self._idle
        self._send_routers({"t": "occ", "occ": occ, "ts": time.time()})
        # live-telemetry piggyback: the tele batch rides the SAME links at
        # the SAME cadence — no extra socket, no extra thread. Only collect
        # once a router is attached, so the span tail is not consumed
        # before anyone can receive it (the ring only re-sends ~3 beats).
        if self._router_cids and _live.live_enabled():
            if self._live_shipper is None:
                self._live_shipper = _live.LiveShipper(
                    self.name,
                    ledger_fn=self.engine.accounting_ledger)
            pays = self._live_shipper.collect()
            if pays:
                self._send_routers({"t": "tele", "pays": pays})
        if (force_store or not self._router_cids
                or now - self._last_occ_store >= _STORE_MIRROR_S):
            self._last_occ_store = now
            with deadline_guard("publish occupancy"):
                self._store.set(k_occ(self._ns, self.name), pack(occ))

    def stop_requested(self) -> bool:
        ctl = k_ctl(self._ns)
        with deadline_guard("poll ctl"):
            if not self._store.check(ctl):
                return False
            rec = unpack(self._store.get(ctl))
        return bool(rec.get("stop"))

    def _check_drain_ctl(self):
        """Adopt the fleet supervisor's drain/resume order for this
        engine (per-engine ctl key), at the slow store-mirror cadence —
        it is control plane, not request path. A beat is forced on every
        EDGE so the router and the supervisor see the new ``draining``/
        ``drained`` state within one mirror period."""
        now = time.monotonic()
        if now - self._last_drain_ctl < _STORE_MIRROR_S:
            return
        self._last_drain_ctl = now
        key = k_ctl_engine(self._ns, self.name)
        with deadline_guard("poll drain ctl"):
            if not self._store.check(key):
                return
            rec = unpack(self._store.get(key))
        want = bool(rec.get("drain"))
        if want != self.draining:
            self.draining = want
            self.publish_occupancy(force_store=True)

    # -- scheduler ----------------------------------------------------------

    def poll_once(self) -> bool:
        """One deterministic worker round: pump the transport, drain new
        requests, advance the engine one step (chaos fence first —
        PADDLE_CHAOS_ENGINE_* can SIGKILL here, mid-decode), publish
        results + occupancy. The occupancy beat is throttled to ~40 Hz on
        the wire (its store mirror far slower): the router samples it far
        slower still, and unthrottled publishes just contend the fabric.
        The request drain check is likewise throttled to ~50 Hz while the
        engine is busy — its internal queue keeps the slots fed between
        checks; an idle engine checks every poll so first dispatch lands
        fast. Returns True while the engine still holds work."""
        self._pump_transport()
        if self._wt_cids:
            self._drain_weights()
        self._check_drain_ctl()
        now = time.monotonic()
        if self.draining:
            # drain order in effect: admit nothing new — undispatched
            # seqs stay unconsumed for the router's evacuate/handoff;
            # in-flight work below still runs to completion
            pass
        elif not self._local_rid or now - self._last_drain >= 0.02:
            self._last_drain = now
            self._drain_requests()
        if self.role == "prefill":
            self._advance_prefill()
        if self._kv_imports:
            self._advance_kv_imports()
        chaos.engine_fence(self.engine.decode_steps)
        t_step = time.monotonic()
        busy = self.engine.step()
        if busy and self._step_floor_s > 0.0:
            # device-step floor: pace the scheduler as if each step were
            # accelerator-bound (host idle while the device runs). Lets
            # CPU-only hosts measure control-plane scaling, and doubles
            # as a crude per-engine rate limiter.
            rem = self._step_floor_s - (time.monotonic() - t_step)
            if rem > 0.0:
                time.sleep(rem)
        published = self._publish_done()
        working = (busy or bool(self._local_rid)
                   or bool(self._prefill_jobs) or bool(self._kv_imports))
        self._idle = not working
        if published or time.monotonic() - self._last_occ_pub >= 0.025:
            self.publish_occupancy(force_store=bool(published))
        return working

    def serve(self, poll_interval: float = 0.005,
              ctl_interval: float = 0.25):
        """Poll until the router broadcasts stop. Idle rounds sleep
        ``poll_interval`` (the engine's own admission backoff bounds the
        pages-starved case); the stop broadcast is only polled every
        ``ctl_interval`` seconds — it is the cold path."""
        last_ctl = -float("inf")
        try:
            while True:
                now = time.monotonic()
                if now - last_ctl >= ctl_interval:
                    last_ctl = now
                    if self.stop_requested():
                        return
                if not self.poll_once():
                    time.sleep(poll_interval)
        finally:
            self._server.close()
            for link in self._kv_links.values():
                link.close()


def build_worker_model(args):
    """Deterministic tiny-GPT build shared by every worker process AND the
    in-process reference engines of the tests/bench: same seed => same
    weights => bit-equal token streams across processes."""
    import paddle_tpu as paddle
    from ..text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(args.model_seed)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_hidden_layers=args.layers, num_attention_heads=args.heads,
        max_position_embeddings=args.max_positions,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
    model.eval()
    return model


def build_arg_parser():
    import argparse

    p = argparse.ArgumentParser("paddle_tpu.serving.worker")
    p.add_argument("--master", default=os.environ.get(
        "PADDLE_SERVING_MASTER", "127.0.0.1:29500"),
        help="host:port of the coordination store (PADDLE_SERVING_MASTER)")
    p.add_argument("--namespace", default=DEFAULT_NAMESPACE)
    p.add_argument("--name", default=None)
    p.add_argument("--role", default="unified", choices=list(ROLES),
                   help="unified = prefill+decode per request; prefill = "
                        "export KV pages and stream them to decode "
                        "workers; decode = import streamed prefills")
    p.add_argument("--kv-wire", default="raw", choices=["raw", "int8"],
                   help="KV-page stream codec: raw is bit-equal, int8 "
                        "absmax-quantizes per [layer, page, head] "
                        "(~4x smaller frames, trajectory-level fidelity)")
    p.add_argument("--poll-interval", type=float, default=0.005)
    p.add_argument("--step-floor-ms", type=float, default=0.0,
                   help="minimum wall time per scheduler step; emulates "
                        "accelerator-bound steps on CPU-only hosts and "
                        "doubles as a crude rate limiter")
    p.add_argument("--warmup", action="store_true",
                   help="pre-build every engine program before serving — "
                        "each prefill bucket, the single-token decode, and "
                        "the verify program when --speculate-k > 0 — so "
                        "placement luck cannot land an XLA compile on the "
                        "request path; with PADDLE_TPU_COMPILE_CACHE set "
                        "the builds load from the persistent AOT cache")
    # model spec (must match the router/bench reference build)
    p.add_argument("--model-seed", type=int, default=7)
    p.add_argument("--vocab", type=int, default=128)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--max-positions", type=int, default=512)
    # engine geometry
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max-length", type=int, default=256)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--speculate-k", type=int, default=0)
    p.add_argument("--no-prefix-cache", action="store_true")
    p.add_argument("--kv-dtype", default="f32")
    p.add_argument("--mp", type=int, default=1,
                   help="shard decode over this many devices' mp axis "
                        "(dp1 x mp mesh over the first mp local devices)")
    return p


def main(argv=None):
    args = build_arg_parser().parse_args(argv)
    from ..runtime import TCPStore

    model = build_worker_model(args)
    mesh = None
    if args.mp > 1:
        import jax

        from ..distributed.mesh import build_mesh

        mesh = build_mesh((1, args.mp), ("dp", "mp"),
                          devices=jax.devices()[:args.mp])
    host, port = args.master.rsplit(":", 1)
    store = TCPStore(host=host, port=int(port), is_master=False, timeout=60.0)
    worker = EngineWorker(
        model, store, name=args.name, namespace=args.namespace,
        step_floor_s=args.step_floor_ms / 1000.0, role=args.role,
        kv_wire=args.kv_wire,
        num_slots=args.slots, max_length=args.max_length,
        page_size=args.page_size, speculate_k=args.speculate_k,
        prefix_cache=not args.no_prefix_cache, kv_dtype=args.kv_dtype,
        mesh=mesh)
    if args.warmup:
        w = worker.engine.warmup()
        print(f"[serving] worker {worker.name} warm "
              f"({w['buckets']} buckets + decode"
              + (" + verify" if w["verify"] else "")
              + f", {w['cache_hits']}/{w['programs']} compile-cache hits)",
              file=sys.stderr, flush=True)
    print(f"[serving] worker {worker.name} (engine {worker.index}, "
          f"{worker.role}) serving via {args.master} + {worker._server.addr}",
          file=sys.stderr, flush=True)
    worker.serve(poll_interval=args.poll_interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())
