"""Router <-> engine-worker coordination protocol over the store.

The serving plane splits control from data. The coordination store (the
SAME TCPStore the training stack rendezvouses on, runtime/py_store.py)
is the GROUND TRUTH for membership and failover: engine workers register
under a namespace, publish occupancy beats, and persist every finished
request's ``done`` key there. The per-request hot path — dispatch,
completion acks, token/KV streams — normally rides the direct streaming
sockets in ``serving/transport.py`` instead; the ``req`` keys below are
the legacy store dataplane, kept fully working behind the router's
``dataplane="store"`` A/B switch and as the fallback when a worker's
socket drops mid-dispatch. Either way the crash-safety contract is the
store's: a ``done`` key is written before the occupancy ack, so a dead
worker's finished work is always harvestable.

Key schema (all under one namespace, default ``__srv``)::

    {ns}/count            engine counter: ``add(key, 1) - 1`` is a fresh
                          engine index (race-free discovery — ``add`` is
                          the store's atomic fetch-and-add)
    {ns}/engine/{i}       registration record of engine index i (carries
                          ``addr`` — the worker's transport listen
                          address the router dials — plus ``role``:
                          prefill | decode | unified, and ``kv_wire``)
    {ns}/occ/{name}       occupancy beat of engine `name` (monotone
                          ``beat`` field; a stalled beat past the grace
                          window means the worker is dead)
    {ns}/req/{name}/{seq} request seq dispatched to engine `name` on the
                          legacy store dataplane or the socket-failure
                          fallback (workers consume their stream in seq
                          order and ack via ``acked_seq``; the streaming
                          transport reuses the SAME seq numbering, so a
                          worker drains wire and store dispatches as one
                          stream).
                          With telemetry on, the record carries a
                          ``trace`` dict — ``{"trace_id", "parent_id",
                          "resubmits", "dispatch_ts"}`` — next to the
                          router-assigned seed; the worker and engine
                          continue that trace (observability/tracing.py)
                          so one request is one span tree across all
                          three processes. Absent when telemetry is off:
                          tracing adds zero wire bytes when disabled.
                          With tenant accounting on
                          (observability/accounting.py), a non-default
                          ``tenant`` label travels the same way — a
                          ``tenant`` + ``slo`` pair on the record (and
                          on disaggregated KV handoff payloads) that the
                          engine meters usage under. Requests without a
                          tenant add zero wire bytes and land on the
                          ledger's ``"-"`` default — the same disabled-
                          path contract as ``trace``.
    {ns}/done/{rid}       completed token stream of router request `rid`
                          (written BEFORE the occupancy ack, so failover
                          can harvest finished work from a dead engine)
    {ns}/ctl              router shutdown broadcast
    {ns}/ctl/{name}       per-engine control record (fleet supervisor
                          drain/resume orders for role flips)

Values are pickled python dicts (``pack``/``unpack``): the store is a
trusted same-job coordination plane, exactly like the launch rendezvous
that already rides it.

Every store op in router.py/worker.py must sit under ``deadline_guard``
— ``scripts/check_robustness.py`` rule 4 enforces it statically, the
same discipline rule 3 applies to reshard collectives.
"""
from __future__ import annotations

import contextlib
import os
import pickle
import sys
import threading
from typing import Optional

DEFAULT_NAMESPACE = "__srv"

#: SLO classes in ascending priority; overload sheds the lowest first.
SLO_CLASSES = ("batch", "standard", "interactive")

#: per-class default deadline budget (seconds from submit); a request
#: still queued past its deadline is shed, not dispatched late.
DEFAULT_DEADLINES = {"interactive": 30.0, "standard": 120.0, "batch": 600.0}

#: Declared per-class service objectives — the targets the live telemetry
#: plane (observability/live.py) burns error budget against. Two
#: objectives per class:
#:
#:   * latency: ``latency_slo`` of requests must finish within
#:     ``latency_target_s`` (e.g. interactive: 95% under 2s). The error
#:     budget is ``1 - latency_slo``; the burn rate is the observed
#:     over-target fraction divided by that budget — 1.0 means the budget
#:     is being consumed exactly as fast as it accrues, >1.0 means an
#:     eventual SLO violation if sustained.
#:   * availability: ``availability_slo`` of admitted requests must
#:     complete (not shed, not failed). Same burn-rate convention.
#:
#: Tuned for the proxy fleet the benches drive (tiny models, CPU XLA);
#: a real deployment would override these per product surface. Every
#: class in SLO_CLASSES has an entry — observability/live.py and the
#: post-hoc trace summary both key off this table, so the live and
#: batch burn rates are definitionally comparable.
SLO_OBJECTIVES = {
    "interactive": {"latency_target_s": 2.0, "latency_slo": 0.95,
                    "availability_slo": 0.999},
    "standard": {"latency_target_s": 10.0, "latency_slo": 0.95,
                 "availability_slo": 0.995},
    "batch": {"latency_target_s": 60.0, "latency_slo": 0.90,
              "availability_slo": 0.99},
}


def k_count(ns: str) -> str:
    return f"{ns}/count"


def k_engine(ns: str, index: int) -> str:
    return f"{ns}/engine/{index}"


def k_occ(ns: str, name: str) -> str:
    return f"{ns}/occ/{name}"


def k_req(ns: str, name: str, seq: int) -> str:
    return f"{ns}/req/{name}/{seq}"


def k_done(ns: str, rid: int) -> str:
    return f"{ns}/done/{rid}"


def k_ctl(ns: str) -> str:
    return f"{ns}/ctl"


def k_ctl_engine(ns: str, name: str) -> str:
    """Per-engine control record (fleet supervisor drain/resume orders).
    A worker polls it at the slow ctl cadence; ``{"drain": True}`` makes
    it stop admitting new dispatches while finishing in-flight work (its
    occupancy beat then advertises ``draining``/``drained`` so the
    router and the supervisor can watch the drain converge)."""
    return f"{ns}/ctl/{name}"


def pack(obj) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def unpack(data):
    return pickle.loads(bytes(data))


def _deadline_seconds() -> float:
    try:
        return float(os.environ.get("PADDLE_TPU_SERVING_TIMEOUT", "120"))
    except ValueError:
        return 120.0


@contextlib.contextmanager
def deadline_guard(what: str, seconds: Optional[float] = None):
    """Bound a router/worker store op the way reshard.deadline_guard
    bounds collectives: a watchdog timer fires if the op stalls past the
    deadline (store peer dead, network wedge), prints a diagnosis naming
    the op, and raises TimeoutError once the block exits — a serving
    control-plane stall becomes a diagnosed failure instead of a silent
    router hang. ``check_robustness.py`` rule 4 statically requires every
    store call site in paddle_tpu/serving to sit inside this guard."""
    limit = _deadline_seconds() if seconds is None else float(seconds)
    fired = threading.Event()

    def _stall():
        fired.set()
        print(f"[serving] store op {what!r} exceeded its {limit:.0f}s "
              "deadline — coordination store unreachable or peer wedged; "
              "raise PADDLE_TPU_SERVING_TIMEOUT for slow fabrics",
              file=sys.stderr, flush=True)

    timer = threading.Timer(limit, _stall)
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()
    if fired.is_set():
        raise TimeoutError(
            f"serving store op {what!r} exceeded its {limit:.0f}s deadline")
