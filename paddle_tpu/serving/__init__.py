"""Multi-engine serving: tensor-parallel decode engines behind an
SLO-aware request router.

Two layers (docs/SERVING.md):

- Each worker wraps one ``inference.DecodeEngine`` — optionally
  mp-sharded over a device mesh (``EngineConfig.mesh``) so the paged KV
  pools split across kv heads under GSPMD — and coordinates through the
  training stack's TCPStore (``serving.protocol`` key schema).
- The ``Router`` admits requests against a bounded queue with SLO
  classes (shed-lowest-first under overload), places them by
  least-outstanding-tokens with prefix affinity, and fails over dead
  engines by resubmitting their unfinished work — bit-equal, because
  every request carries a router-assigned sampling seed.
"""
from .protocol import (DEFAULT_DEADLINES, DEFAULT_NAMESPACE, SLO_CLASSES,
                       deadline_guard)
from .router import Router, RouterConfig, RouterRequest
from .worker import EngineWorker

__all__ = [
    "Router", "RouterConfig", "RouterRequest", "EngineWorker",
    "SLO_CLASSES", "DEFAULT_DEADLINES", "DEFAULT_NAMESPACE",
    "deadline_guard",
]
