"""Multi-engine serving: tensor-parallel decode engines behind an
SLO-aware request router, on a streaming dataplane.

Three layers (docs/SERVING.md):

- Each worker wraps one ``inference.DecodeEngine`` — optionally
  mp-sharded over a device mesh (``EngineConfig.mesh``) so the paged KV
  pools split across kv heads under GSPMD — registers through the
  training stack's TCPStore (``serving.protocol`` key schema), and
  serves its hot path over a persistent transport socket. Workers take a
  role: ``unified`` (classic), ``prefill`` (export KV pages and stream
  them to decode workers), or ``decode`` (import streamed prefills).
- The ``transport`` module is the dataplane: length-prefixed frames over
  plain TCP carrying dispatches, completion acks, occupancy heartbeats,
  and KV-page streams, with jittered-backoff reconnects. The store stays
  the membership/failover ground truth (done-before-ack is a store-write
  ordering), so losing a socket can never lose a request.
- The ``Router`` admits requests against a bounded queue with SLO
  classes (shed-lowest-first under overload), places them by
  least-outstanding-tokens with prefix affinity — long prompts go to a
  prefill worker and stream KV to their decode worker — and fails over
  dead engines by resubmitting their unfinished work — bit-equal,
  because every request carries a router-assigned sampling seed.
- Above the routers, the ``FrontierRouter`` (``frontier`` module)
  federates several leaf routers: tenants shard onto leaves by sticky
  rendezvous hashing, per-tenant token-bucket quotas shed abusive
  traffic before it can burn a class error budget, and hot tenants
  spread across their top-ranked leaves. The ``replay`` module is the
  matching workload generator — deterministic million-request arrival
  streams against real leaves or in-process stub fleets
  (docs/REPLAY.md).
- The ``online`` module closes the continuous-learning loop: a trainer
  publishes versioned weight epochs into live engines as journaled,
  seq-acked ``wt`` streams that flip by pointer swap at a request
  boundary — zero drain, zero recompile (docs/ONLINE.md).
"""
from .protocol import (DEFAULT_DEADLINES, DEFAULT_NAMESPACE, SLO_CLASSES,
                       deadline_guard)
from .frontier import FrontierConfig, FrontierRouter, rendezvous_rank
from .online import (EngineSink, OnlineCoordinator, WireEngineSink,
                     rollout_round)
from .router import Router, RouterConfig, RouterRequest
from .transport import TransportClient, TransportServer
from .worker import EngineWorker

__all__ = [
    "Router", "RouterConfig", "RouterRequest", "EngineWorker",
    "FrontierRouter", "FrontierConfig", "rendezvous_rank",
    "TransportClient", "TransportServer",
    "OnlineCoordinator", "EngineSink", "WireEngineSink", "rollout_round",
    "SLO_CLASSES", "DEFAULT_DEADLINES", "DEFAULT_NAMESPACE",
    "deadline_guard",
]
