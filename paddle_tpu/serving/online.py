"""Online continuous-learning plane: zero-drain versioned weight flips.

The trainer publishes weight epoch ``E+1`` into live decode engines
while they keep serving epoch ``E``: each engine stages the new values
into a double-buffered shadow param set (``engine.begin_weight_epoch`` /
``stage_weight``), then flips by pointer swap at a request boundary
(``promote_epoch``). The compiled-call state value list is jit arg #0 —
never part of the AOT cache key — so a flip recompiles NOTHING; it is a
different value list under the same executable. In-flight requests
finish on the epoch they started (per-slot epoch pin in the engine), new
admissions take ``E+1``, and greedy decode stays bit-equal per
(seed, epoch).

Weights travel as seq-acked ``wt`` frames (``transport.encode_wt_frame``,
bf16 wire by default) over the SAME persistent transport as the request
dataplane — in process via :class:`EngineSink`, over a socket via
:class:`WireEngineSink` (the worker applies frames between engine
steps). Only changed leaves go on the wire: the coordinator keeps a
per-engine digest of the last-sent payload per leaf and skips bit-equal
ones, so a fine-tune that touches two layers streams two layers.

Every flip is a journaled two-phase transaction in the fleet
supervisor's :class:`FlipJournal` (``weights_current.json``), with
``chaos.weight_fence`` fault points at each fence so soaks can SIGKILL
the publisher mid-stream:

    publish -> stream -> [leaf sends, fence ``wt:<seq>``] -> commit
            -> swap -> finalize -> close(committed)

A crash before ``commit`` rolls BACK (``recover`` discards engine
shadows and retires the doc); a crash at/past ``commit`` rolls FORWARD:
``recover`` retires the doc and the deterministic trainer's idempotent
convergence loop (:meth:`OnlineCoordinator.ensure_epoch`) re-publishes
the epoch — the engines' ``epoch <= live`` no-op guards make the flip
exactly-once however many times the stream is replayed, and
``close_weights`` dedups history by id so one committed entry per epoch
survives. Failure matrix: docs/ONLINE.md.

``check_robustness.py`` rule 9 statically pins the flip to the
transaction: ``promote_epoch``/``discard_shadow`` may only be called
from :func:`apply_wt_frame`, and building a swap/discard frame requires
the enclosing function to advance or close the weight journal.

End to end, decode engines double as rollout workers::

    out = rollout_round(coord, epoch, generate_fn=sample_prompts,
                        reward_fn=score, train_fn=sgd_steps)

This module is the single writer of the ``online_*`` metric family and
the ``weight_flip`` span (scripts/check_observability.py).
"""
from __future__ import annotations

import hashlib
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import observability as _obs
from ..distributed import reshard as _reshard
from ..testing import chaos
from .protocol import deadline_guard
from .transport import (TransportClient, decode_wt_frame, encode_wt_ack,
                        encode_wt_frame)

__all__ = ["WEIGHT_CHANNEL", "apply_wt_frame", "EngineSink",
           "WireEngineSink", "OnlineCoordinator", "rollout_round"]

#: the wt stream's SeqChannels channel name (per coordinator connection)
WEIGHT_CHANNEL = "wt"


def apply_wt_frame(engine, frame: dict) -> dict:
    """Apply one decoded ``wt`` frame to a live engine and build its ack.

    This is the ONLY call site of ``engine.promote_epoch`` /
    ``engine.discard_shadow`` in the serving package (check_robustness.py
    rule 9): every pointer swap an engine ever performs traces back to a
    journaled weight transaction that built the frame. Exactly-once falls
    out of the engine's no-op guards — a replayed ``begin`` for a
    committed epoch returns applied=False and the following ``leaf``
    frames are dropped on the floor (no open shadow), a replayed ``swap``
    acks applied=False.
    """
    kind, epoch, name, arr, _meta = decode_wt_frame(frame)
    applied: Optional[bool] = None
    if kind == "begin":
        applied = engine.begin_weight_epoch(epoch)
    elif kind == "leaf":
        if (engine._shadow is not None
                and engine._shadow["epoch"] == int(epoch)):
            engine.stage_weight(name, arr)
            applied = True
        else:
            applied = False  # replay onto a committed epoch: drop
    elif kind == "swap":
        applied = engine.promote_epoch(epoch)
    elif kind == "discard":
        applied = engine.discard_shadow(epoch)
    # the ack echoes the frame kind and the engine's post-apply serving
    # epoch: ``live`` is the only field that proves what the engine
    # serves — a begin/leaf/discard ack carries the pre-flip epoch
    # there, so the publisher can never mistake "shadow opened" for
    # "epoch flipped" (encode_wt_ack documents per-kind semantics)
    return encode_wt_ack(frame["ch"], frame["seq"], epoch, applied=applied,
                         kind=kind, live=int(engine.weight_epoch))


class EngineSink:
    """In-process sink: frames apply synchronously to a local engine.

    Used by the colocated trainer path (train and serve in one process)
    and by the bench/offline-parity harnesses — the same frames, the same
    :func:`apply_wt_frame` chokepoint, zero sockets.
    """

    def __init__(self, engine, name: str = "engine0"):
        self.engine = engine
        self.name = name
        #: highest epoch this sink is known to serve (ack-derived)
        self.known_epoch = int(engine.weight_epoch)
        self._acks: List[dict] = []

    def send(self, frame: dict) -> bool:
        ack = apply_wt_frame(self.engine, frame)
        self._acks.append(ack)
        return True

    def pump(self) -> None:  # wire parity: nothing to poll
        pass

    def collect_acks(self) -> List[dict]:
        out, self._acks = self._acks, []
        return out

    def close(self) -> None:
        pass


class WireEngineSink:
    """Socket sink: frames ride a persistent :class:`TransportClient` to
    an :class:`~paddle_tpu.serving.worker.EngineWorker`, which applies
    them between engine steps and acks per seq. ``pump`` drains acks;
    the coordinator blocks on them under a deadline guard."""

    def __init__(self, addr: str, name: str):
        self.client = TransportClient(addr)
        self.name = name
        self.known_epoch = -1
        self._acks: List[dict] = []

    def send(self, frame: dict) -> bool:
        return self.client.send(frame)

    def pump(self) -> None:
        for fr in self.client.poll():
            if fr.get("t") == "wt_ack":
                self._acks.append(fr)

    def collect_acks(self) -> List[dict]:
        self.pump()
        out, self._acks = self._acks, []
        return out

    def close(self) -> None:
        self.client.close()


class OnlineCoordinator:
    """Trainer-side publisher of versioned weight epochs into a fleet of
    live engines.

    One instance owns the journal's weight transaction, the per-engine
    wt seq streams, and the per-engine last-sent digests that turn a full
    param set into a delta set. ``sinks`` maps engine name to an
    :class:`EngineSink` or :class:`WireEngineSink`.

    Size ``ack_timeout_s`` to the model: a worker applies at most
    ``EngineWorker._WT_FRAMES_PER_POLL`` wt frames per poll round (so
    decode never stalls), so a full-delta stream needs about
    ``leaves / _WT_FRAMES_PER_POLL`` rounds — for many-leaf models
    raise the timeout above the 30s default accordingly.
    """

    def __init__(self, journal, sinks: Dict[str, object], *,
                 wire: str = "bf16", ack_timeout_s: float = 30.0,
                 yield_fn=None):
        self.journal = journal
        self.sinks = dict(sinks)
        self.wire = wire
        self.ack_timeout_s = float(ack_timeout_s)
        #: cooperative-yield hook for SINGLE-PROCESS embeddings (benches,
        #: tests) where the publisher and an in-process engine share one
        #: thread: called between leaf encodes so the engine keeps
        #: stepping while the delta set is prepared. The wire topology
        #: gets this for free — encode runs on the trainer host.
        self._yield_fn = yield_fn
        #: per engine: leaf name -> sha1 of the last payload it acked
        self._digests: Dict[str, Dict[str, str]] = {
            name: {} for name in self.sinks}
        #: per engine: next wt seq on its stream
        self._seq: Dict[str, int] = {name: 0 for name in self.sinks}
        #: global frame counter driving the ``wt:<n>`` chaos fences
        self._frames_sent = 0

    # -- delta computation --------------------------------------------------

    def _encode_leaves(self, params: Dict[str, np.ndarray],
                       layouts: Optional[dict] = None,
                       src_mesh=None, dst_mesh=None, dst_spec=None):
        """Encode every leaf once (the wire payload is shared across
        engines) and fingerprint it. When the trainer hands over its
        recorded layouts, each leaf carries the reshard read-spec
        (``plan_restore_spec``) re-expressing the TRAINING mesh's shard
        granularity onto the serving mesh — the engine replicates either
        way, but the spec bounds what each serving host reads."""
        leaves = []
        for name in sorted(params):
            if self._yield_fn is not None:
                self._yield_fn()
            arr = np.asarray(params[name])
            meta = None
            if layouts and name in layouts and dst_mesh is not None:
                rec = layouts[name]
                spec = _reshard.plan_restore_spec(
                    rec, src_mesh, dst_mesh,
                    dst_spec if dst_spec is not None
                    else rec.pspec())
                meta = {"spec": [list(p) for p in
                                 _reshard._norm_spec(spec, arr.ndim)]}
            payload = encode_wt_frame("?", 0, "leaf", 0, name=name,
                                      arr=arr, wire=self.wire)["x"]
            h = hashlib.sha1(np.ascontiguousarray(payload["x"]).tobytes())
            if "scale" in payload:
                h.update(np.ascontiguousarray(payload["scale"]).tobytes())
            leaves.append((name, payload, meta, h.hexdigest(),
                           int(arr.nbytes)))
        return leaves

    def _send(self, sink, frame: dict) -> None:
        self._frames_sent += 1
        chaos.weight_fence(f"wt:{self._frames_sent}")
        sink.send(frame)

    def _wait_acks(self, want: Dict[str, set], doc: dict) -> None:
        """Block until every engine acked every listed seq (its worker
        applies frames between steps, so this bounds the stream, not the
        flip — decode continues throughout)."""
        with deadline_guard("wt stream acks", self.ack_timeout_s):
            deadline = time.monotonic() + self.ack_timeout_s
            while any(want.values()):
                for name, pending in want.items():
                    if not pending:
                        continue
                    sink = self.sinks[name]
                    for ack in sink.collect_acks():
                        # known_epoch advances ONLY from what the ack
                        # proves the engine serves. ``live`` is the
                        # engine's post-apply serving epoch (any kind);
                        # without it, only a swap ack counts — begin/
                        # leaf/discard acks set ``applied`` too, but
                        # "shadow opened" is not "epoch flipped", and
                        # treating it as such let a pre-commit failure
                        # leave known_epoch at the new epoch so the
                        # ensure_epoch retry no-op'd on stale weights.
                        if "live" in ack:
                            sink.known_epoch = max(
                                sink.known_epoch, int(ack["live"]))
                        elif (ack.get("kind") == "swap"
                                and ack.get("applied") is not None):
                            # swap True = flipped now; swap False = the
                            # exactly-once no-op (already at/past)
                            sink.known_epoch = max(
                                sink.known_epoch, int(ack["epoch"]))
                        seq = int(ack["seq"])
                        if seq not in pending:
                            # stale ack (e.g. a rolled-back stream's
                            # discard): seqs are never reused, so it
                            # cannot be one of ours
                            continue
                        pending.discard(seq)
                        doc["acked"][name] = max(
                            doc["acked"].get(name, -1), seq)
                if any(want.values()):
                    if time.monotonic() > deadline:
                        missing = {n: sorted(p)[:4]
                                   for n, p in want.items() if p}
                        raise TimeoutError(
                            f"wt stream unacked past "
                            f"{self.ack_timeout_s:.0f}s: {missing}")
                    time.sleep(0.002)

    # -- the journaled flip transaction -------------------------------------

    def publish_epoch(self, epoch: int, params: Dict[str, np.ndarray], *,
                      layouts: Optional[dict] = None, src_mesh=None,
                      dst_mesh=None, dst_spec=None) -> dict:
        """Stream epoch ``epoch``'s (delta) weights to every engine and
        flip them, as one journaled transaction. Returns the closed
        journal entry. Raises on a pre-commit failure AFTER rolling the
        engines back (shadows discarded, doc retired ``rolled_back``);
        past commit the transaction only rolls forward."""
        epoch = int(epoch)
        t0 = time.monotonic()
        handle = _obs.start_span("weight_flip", epoch=epoch,
                                 engines=len(self.sinks))
        doc = {"id": f"wt-{epoch}", "epoch": epoch,
               "engines": sorted(self.sinks), "leaves": 0,
               "wire": self.wire, "bytes": 0, "acked": {}}
        self.journal.begin_weights(doc)
        chaos.weight_fence("publish")
        leaves = self._encode_leaves(params, layouts, src_mesh,
                                     dst_mesh, dst_spec)
        try:
            # -- stream: begin + changed leaves, per engine ----------------
            self.journal.advance_weights(doc, "stream")
            chaos.weight_fence("stream")
            # drop acks left over from a previous transaction (e.g. the
            # unawaited discards of a rollback) so they are never read
            # as this stream's — seqs are disjoint, but stale ``live``
            # values would be harmless and stale seq bookkeeping is not
            for sink in self.sinks.values():
                sink.collect_acks()
            want: Dict[str, set] = {}
            for name, sink in self.sinks.items():
                seqs = set()
                seq = self._seq[name]
                self._send(sink, encode_wt_frame(
                    WEIGHT_CHANNEL, seq, "begin", epoch))
                seqs.add(seq)
                seq += 1
                sent = self._digests[name]
                for leaf, payload, meta, digest, nbytes in leaves:
                    if sent.get(leaf) == digest:
                        continue  # bit-equal to what this engine holds
                    frame = {"t": "wt", "ch": WEIGHT_CHANNEL, "seq": seq,
                             "kind": "leaf", "epoch": epoch,
                             "name": leaf, "x": payload}
                    if meta:
                        frame["meta"] = meta
                    self._send(sink, frame)
                    doc["leaves"] += 1
                    doc["bytes"] += nbytes
                    _obs.inc("online_wt_bytes_total", nbytes,
                             engine=name)
                    seqs.add(seq)
                    seq += 1
                self._seq[name] = seq
                want[name] = seqs
            self._wait_acks(want, doc)
            # -- commit: the journal decides BEFORE the engines flip, so a
            # crash from here on rolls forward (re-publish converges) ------
            self.journal.advance_weights(doc, "commit")
            chaos.weight_fence("commit")
        except Exception:
            # pre-commit failure: discard every engine's shadow and retire
            # the doc as rolled back; nothing flipped. The discards are
            # best-effort — a dead sink must not mask the streaming error
            # (a shadow that survives an unreachable discard is replaced
            # wholesale by the next publish's begin frame)
            for name, sink in self.sinks.items():
                seq = self._seq[name]
                try:
                    sink.send(encode_wt_frame(
                        WEIGHT_CHANNEL, seq, "discard", epoch))
                except Exception:
                    pass
                self._seq[name] = seq + 1
            self.journal.close_weights(doc, "rolled_back")
            _obs.inc("online_flips_total", outcome="rolled_back")
            _obs.event("weight_flip_rollback", epoch=epoch)
            _obs.end_span(handle, outcome="rolled_back")
            raise
        # -- swap: pointer-flip orders, exactly-once via the no-op guard --
        self.journal.advance_weights(doc, "swap")
        chaos.weight_fence("swap")
        want = {}
        for name, sink in self.sinks.items():
            seq = self._seq[name]
            self._send(sink, encode_wt_frame(
                WEIGHT_CHANNEL, seq, "swap", epoch))
            self._seq[name] = seq + 1
            want[name] = {seq}
        self._wait_acks(want, doc)
        self.journal.advance_weights(doc, "finalize")
        chaos.weight_fence("finalize")
        # only now do the digests learn the new payloads: a rolled-back
        # stream must re-send its leaves next time
        for name in self.sinks:
            sent = self._digests[name]
            for leaf, _payload, _meta, digest, _nbytes in leaves:
                sent[leaf] = digest
        self.journal.close_weights(doc, "committed")
        dur = time.monotonic() - t0
        _obs.set_gauge("online_weight_epoch", float(epoch))
        _obs.observe("online_flip_seconds", dur)
        _obs.inc("online_flips_total", outcome="committed")
        _obs.event("weight_flip_commit", epoch=epoch,
                   leaves=doc["leaves"], bytes=doc["bytes"])
        _obs.end_span(handle, outcome="committed")
        return dict(doc, outcome="committed", seconds=dur)

    # -- crash recovery -----------------------------------------------------

    def recover(self) -> Optional[str]:
        """Resolve a weight transaction left open by a crash. Before
        ``commit``: discard any surviving engine shadows and retire the
        doc ``rolled_back``. At/past ``commit``: retire it
        ``rolled_forward`` — the shadow died with the publisher, so the
        flip itself converges through :meth:`ensure_epoch`'s idempotent
        re-publish, not through a blind swap replay. Returns the outcome
        or None when no transaction was pending."""
        from ..distributed.fleet.supervisor import (WEIGHT_COMMIT_INDEX,
                                                    WEIGHT_FENCES)
        doc = self.journal.pending_weights()
        if doc is None:
            return None
        epoch = int(doc["epoch"])
        past_commit = (WEIGHT_FENCES.index(doc.get("fence", "publish"))
                       >= WEIGHT_COMMIT_INDEX)
        for name, sink in self.sinks.items():
            seq = self._seq[name]
            try:
                sink.send(encode_wt_frame(
                    WEIGHT_CHANNEL, seq, "discard", epoch))
            except Exception:
                pass  # best-effort: recovery must retire the doc even
                # when an engine is unreachable; its shadow is replaced
                # by the next publish's begin frame
            self._seq[name] = seq + 1
        # the restarted publisher holds no digests for these engines, so
        # the next publish re-sends full state — correct by construction
        outcome = "rolled_forward" if past_commit else "rolled_back"
        self.journal.close_weights(doc, outcome)
        _obs.inc("online_flips_total", outcome=outcome)
        _obs.event("weight_flip_rollback", epoch=epoch, recovered=True,
                   outcome=outcome)
        return outcome

    def ensure_epoch(self, epoch: int,
                     params: Dict[str, np.ndarray], **kw) -> dict:
        """Idempotent convergence: recover any crashed transaction, then
        (re-)publish until every engine serves ``epoch``. The trainer is
        deterministic, so a re-publish streams bit-equal values; the
        engines' no-op guards make the flip exactly-once."""
        epoch = int(epoch)
        self.recover()
        for sink in self.sinks.values():
            sink.pump()
        if all(s.known_epoch >= epoch for s in self.sinks.values()):
            return {"id": f"wt-{epoch}", "epoch": epoch,
                    "outcome": "already_current"}
        return self.publish_epoch(epoch, params, **kw)


def rollout_round(coord: OnlineCoordinator, epoch: int, *,
                  generate_fn: Callable[[], Sequence],
                  reward_fn: Callable[[object], float],
                  train_fn: Callable[[Sequence, Sequence[float]],
                                     Dict[str, np.ndarray]]) -> dict:
    """One turn of the continuous-learning crank: the decode engines
    double as rollout workers. ``generate_fn`` samples rollouts from the
    live fleet (epoch ``epoch - 1``), ``reward_fn`` scores each one
    (pluggable — a verifier, a preference model, a unit test), and
    ``train_fn`` folds (rollouts, rewards) into the trainer and returns
    the updated param dict, which is then flipped into the fleet as
    ``epoch``. Returns the closed journal entry."""
    rollouts = list(generate_fn())
    rewards = [float(reward_fn(r)) for r in rollouts]
    params = train_fn(rollouts, rewards)
    return coord.ensure_epoch(epoch, params)
