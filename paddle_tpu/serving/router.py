"""SLO-aware, role-aware request router over a fleet of engine workers.

The router is the serving control plane: clients submit prompts tagged
with an SLO class, the router admits or sheds them against a bounded
queue, and each ``pump()`` round dispatches queued work to the live
engine fleet discovered through the coordination store. On the default
``streaming`` dataplane the router keeps one persistent transport
connection per worker (serving/transport.py): dispatches go out as
batched wire frames, completions and occupancy beats come back on the
same sockets, and the store is demoted to membership + failover ground
truth (``dataplane="store"`` keeps the legacy store-key hot path for
A/B runs; a worker whose socket drops gets its frames re-written as
store keys, so the wire is never a new way to lose work).

Placement is least-outstanding-tokens — the engine-reported occupancy
plus the load this router dispatched but the engine has not yet acked —
softened by prefix affinity: a request whose chain-hashed prompt blocks
were last served by a particular engine routes back there (reusing that
engine's paged prefix cache) unless the load skew exceeds the affinity
slack. The least-loaded engine comes off a lazy-invalidation min-heap
(``dispatch_mode="heap"``, O(log E) per dispatch): loads are computed
once per dispatch round and updated incrementally as work is placed, so
a burst of R requests over E engines costs O(R log E) instead of the
O(R·E·inflight) full rescan. ``dispatch_mode="scan"`` keeps the original
scan as the bit-identical placement oracle for A/B runs — the heap's
(load, engine index) ordering reproduces the scan's tie-break exactly.

Workers registered with ``role="prefill"`` never decode: long
prompts (``prefill_threshold_tokens``) are placed on the prefill worker
with the shallowest queue, which streams the finished KV pages straight
to the chosen decode worker (``kv_to`` in the dispatch record); short
prompts — and everything, when no prefill worker is registered — take
the classic unified path.

Overload policy: when the queue is full an incoming request preempts the
youngest request of a strictly lower SLO class, otherwise it is itself
shed. Shedding is always explicit — a counter, an event, and a
``RuntimeError`` from ``result`` naming the reason (queue_full or
deadline) — never a silent drop.

Failover: a worker whose occupancy beat stalls past the grace window is
declared dead (beats ride the wire as heartbeats AND the store as the
slow mirror; either source only counts when the beat ADVANCES). Its
finished work is harvested from ``done`` keys (workers write those
before acking — the wire ``done`` frames are an echo, the store is the
ground truth), and everything else is resubmitted to the FRONT of its
class queue. Reruns are bit-equal because the router stamps every
request with an explicit sampling seed at admission, so placement —
including a rerun of a disaggregated request as a unified one — is
invisible in the token streams (no loss, no duplicates, no drift).
Dispatch frames lost in flight are retransmitted once the worker's
``acked_seq`` stalls past ``retransmit_s`` (idempotent: workers skip
consumed seqs), with a store-key write alongside so even a half-open
socket cannot wedge a request.

This module is the single writer of the ``serving_router_*`` telemetry
family (scripts/check_observability.py enforces that), and every store
call sits under ``protocol.deadline_guard`` (check_robustness.py rule 4).

Tracing: with telemetry enabled the router mints one trace per admitted
request and owns its router-side spans — ``srv_request`` (the root,
submit through result), ``srv_admit``, ``srv_queue``, ``srv_dispatch``
and ``srv_retry`` (failover resubmission windows, retry=True). The trace
context rides the dispatch record (protocol.py) so the worker and engine
continue the same tree; failover reruns attach under the same root,
never minting a second one.
"""
from __future__ import annotations

import heapq
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import observability as _obs
from ..observability import accounting as _acct
from ..observability import live as _live
from ..inference.engine import PrefixRegistry, SamplingParams
from .protocol import (DEFAULT_DEADLINES, DEFAULT_NAMESPACE, SLO_CLASSES,
                       deadline_guard, k_ctl, k_done, k_engine, k_occ,
                       k_req, k_count, pack, unpack)
from .transport import TransportClient

__all__ = ["Router", "RouterConfig", "RouterRequest"]

#: bound on the prefix-affinity LRU (block-key -> engine name entries)
_AFFINITY_CAP = 65536

#: store-mirror cadence: how often the streaming router re-reads the
#: store occupancy copies (wire beats are the hot liveness signal)
_STORE_MIRROR_S = 0.25


@dataclass
class RouterConfig:
    namespace: str = DEFAULT_NAMESPACE
    #: "streaming" rides persistent transport sockets; "store" is the
    #: legacy store-key dataplane (kept for A/B benches and fallback)
    dataplane: str = "streaming"
    #: total queued (not yet dispatched) requests across all SLO classes
    queue_limit: int = 64
    #: seconds from submit before a still-queued request is shed, per class
    deadlines: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_DEADLINES))
    #: occupancy beat staleness past which an engine is declared dead
    engine_grace_s: float = 5.0
    #: outstanding-token skew an affinity route may cost before the
    #: router abandons cache reuse for load balance
    affinity_slack_tokens: int = 512
    #: dispatched-but-unfinished requests allowed per engine
    #: (0 = twice the engine's slot count)
    max_inflight_per_engine: int = 0
    #: prompt block size for affinity chain hashes — match the engines'
    #: page_size or affinity keys never line up with their prefix caches
    page_size: int = 16
    #: prompts at least this long go to a prefill-role worker (when one
    #: is alive) and stream their KV pages to the decode worker; shorter
    #: prompts prefill where they decode
    prefill_threshold_tokens: int = 64
    #: seconds of acked_seq stall before unacked wire dispatches are
    #: retransmitted (and mirrored to the store)
    retransmit_s: float = 1.0
    #: base of the per-request sampling seeds the router assigns so
    #: reruns after failover are bit-equal on any engine
    seed: int = 0
    #: engine-selection strategy: "heap" pops the least-loaded engine
    #: off a lazy-invalidation min-heap rebuilt once per dispatch round
    #: (O(log E) per placement); "scan" is the original full O(E) scan,
    #: kept as the bit-identical placement oracle for A/B runs
    dispatch_mode: str = "heap"
    #: per-pump budget of done-key store probes during harvest (0 =
    #: unbounded). A rotating engine cursor carries the scan across
    #: pumps so deep inflight books make progress fairly instead of
    #: stalling the pump on one store round-trip per in-flight rid.
    harvest_budget: int = 256
    #: keep resolved requests in the book so ``status``/``result`` work
    #: after the fact. The replay harness turns this off (reading
    #: results through ``on_resolve`` instead) so million-request runs
    #: stay memory-bounded.
    retain_results: bool = True


@dataclass
class RouterRequest:
    rid: int
    prompt: np.ndarray
    params: SamplingParams
    slo: str
    submit_t: float
    deadline_t: float
    block_keys: List[bytes]
    status: str = "queued"  # queued | dispatched | done | failed | shed
    #: cost-attribution label (observability/accounting.py); "-" = the
    #: untagged default, which adds zero wire bytes to dispatch records
    tenant: str = "-"
    engine: Optional[str] = None
    seq: int = -1
    tokens: Optional[np.ndarray] = None
    error: Optional[str] = None
    shed_reason: Optional[str] = None
    #: clock stamp of the dispatch that placed this request on an engine
    #: (None while queued / for sheds) — admission latency is
    #: ``dispatch_t - submit_t``
    dispatch_t: Optional[float] = None
    finish_t: Optional[float] = None
    resubmits: int = 0
    trace_id: Optional[str] = None
    #: disaggregated path: name of the prefill engine streaming KV pages
    #: to ``engine`` (None on the unified path)
    kv_from: Optional[str] = None
    #: engine whose seq stream carries this dispatch (the prefill engine
    #: for disaggregated requests) + the wire record for retransmits
    wire_engine: Optional[str] = None
    wire_rec: Optional[dict] = None


@dataclass
class _EngineState:
    name: str
    index: int
    record: dict
    occ: dict = field(default_factory=dict)
    beat: int = -1
    acked_seq: int = 0
    next_seq: int = 0
    #: engine-reported completions already scanned for (-1 = never scanned)
    harvested_done: int = -1
    last_change: float = 0.0
    alive: bool = True
    #: rid -> RouterRequest, dispatch order (oldest first). Disaggregated
    #: requests appear in BOTH their prefill and decode engine's map
    #: until the relay/done frame retires them.
    inflight: "OrderedDict[int, RouterRequest]" = field(
        default_factory=OrderedDict)
    #: streaming dataplane: persistent connection to this worker
    link: Optional[TransportClient] = None
    #: link.reconnects value the last hello was sent on (-1 = never)
    hello_sent: int = -1
    #: dispatch records built this pump, flushed as one batched frame
    outbox: List[dict] = field(default_factory=list)
    #: monotonic stamp of the last ack progress (retransmit timer)
    last_ack_t: float = 0.0

    @property
    def role(self) -> str:
        return self.record.get("role", "unified")

    @property
    def draining(self) -> bool:
        """Fleet-supervisor drain order in effect (engine-advertised via
        its occupancy beat): finish in-flight work, place nothing new."""
        return bool(self.occ.get("draining"))

    @property
    def addr(self) -> Optional[str]:
        return self.record.get("addr")


class Router:
    """Admit, place, and track requests across the registered engines."""

    def __init__(self, store, config: Optional[RouterConfig] = None,
                 clock: Optional[Callable[[], float]] = None,
                 **overrides):
        if config is None:
            config = RouterConfig(**overrides)
        elif overrides:
            raise ValueError("pass config= or field overrides, not both")
        for cls in config.deadlines:
            if cls not in SLO_CLASSES:
                raise ValueError(f"unknown SLO class {cls!r}")
        if config.dataplane not in ("streaming", "store"):
            raise ValueError(
                f"dataplane must be streaming|store, got "
                f"{config.dataplane!r}")
        if config.dispatch_mode not in ("heap", "scan"):
            raise ValueError(
                f"dispatch_mode must be heap|scan, got "
                f"{config.dispatch_mode!r}")
        self.config = config
        self._store = store
        #: injectable time source (the replay harness drives a virtual
        #: clock through here so deadline sheds, liveness grace, and
        #: retransmit timers are deterministic functions of the workload
        #: instead of wall time). None = real time: perf_counter for
        #: request timing, monotonic for liveness.
        self._clock = clock
        self._ns = config.namespace
        self._engines: Dict[str, _EngineState] = {}
        self._by_index: Dict[int, _EngineState] = {}
        self._queues: Dict[str, deque] = {c: deque() for c in SLO_CLASSES}
        self._requests: Dict[int, RouterRequest] = {}
        self._affinity: "OrderedDict[bytes, str]" = OrderedDict()
        self._next_rid = 0
        self._known_engines = 0
        self._last_occ_read = -float("inf")
        #: rid -> open span handles ("root", "queue", "retry"); entries
        #: exist only while telemetry is on and the request is unresolved
        self._tspans: Dict[int, dict] = {}
        self.counters = {"submitted": 0, "done": 0, "failed": 0, "shed": 0,
                         "dispatched": 0, "failover_resubmits": 0,
                         "affinity_hits": 0, "engines_lost": 0,
                         "retransmits": 0, "disagg_dispatches": 0}
        #: live-telemetry aggregator (observability/live.py), created
        #: lazily on the first pump with the plane enabled; stays None —
        #: one env dict lookup per pump — when it is off
        self._live_agg: Optional[_live.LiveAggregator] = None
        #: router-side tenant ledger (shed attribution), created lazily
        #: on the first submit with accounting enabled
        self._acct: Optional[_acct.TenantLedger] = None
        #: called with each request as it resolves (done/failed/shed) —
        #: the replay harness's completion tap; with
        #: ``retain_results=False`` this is the only way results leave
        #: the router
        self.on_resolve: Optional[Callable[[RouterRequest], None]] = None
        #: harvest rotation: which engine the budgeted done-key scan
        #: resumes from next pump
        self._harvest_cursor = 0
        #: False when a front tier (serving/frontier.py) owns the shared
        #: live aggregator: this leaf feeds tenant deltas but leaves
        #: queue gauges + the health-file tick to the frontier
        self._live_driver = True

    @property
    def _streaming(self) -> bool:
        return self.config.dataplane == "streaming"

    def _now(self) -> float:
        """Request-timing clock (submit/dispatch/finish/deadlines)."""
        return self._clock() if self._clock is not None \
            else time.perf_counter()

    def _mono(self) -> float:
        """Liveness clock (beats, grace windows, retransmit timers).
        Same source as ``_now`` when a virtual clock is injected."""
        return self._clock() if self._clock is not None \
            else time.monotonic()

    # -- admission -----------------------------------------------------------

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               slo: str = "standard", tenant: Optional[str] = None,
               **sampling) -> int:
        """Admit a request (or shed it under overload). Returns its rid;
        a shed request keeps the rid so ``status``/``result`` can report
        the rejection. ``tenant`` labels the request for per-tenant cost
        accounting (docs/OBSERVABILITY.md §11); absent it attributes to
        the "-" default and adds zero wire bytes."""
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {slo!r}; expected one of {SLO_CLASSES}")
        if params is None:
            params = SamplingParams(**sampling)
        elif sampling:
            raise ValueError("pass params= or sampling kwargs, not both")
        if self._acct is None and _acct.enabled():
            self._acct = _acct.TenantLedger()
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        if params.seed is None:
            # explicit seed => bit-equal streams on ANY engine, which is
            # what makes failover reruns invisible in the results
            params = replace(params, seed=self.config.seed * 1_000_003
                             + self._next_rid)
        now = self._now()
        req = RouterRequest(
            rid=self._next_rid, prompt=prompt, params=params, slo=slo,
            submit_t=now,
            deadline_t=now + self.config.deadlines.get(
                slo, DEFAULT_DEADLINES[slo]),
            block_keys=PrefixRegistry.block_keys(
                prompt, self.config.page_size))
        # normalize unconditionally: every downstream consumer (shed
        # attribution, quota buckets, ledger cells) keys on the
        # NORMALIZED label, so a raw "" / "  acme " / control-char label
        # can never mint a ledger row distinct from its canonical form —
        # and the untagged "-" default can never alias a tagged tenant
        req.tenant = _acct.normalize_tenant(tenant)
        self._next_rid += 1
        self._requests[req.rid] = req
        self.counters["submitted"] += 1
        _obs.inc("serving_router_requests_total")
        if _obs.enabled():
            # one trace per admitted request; the id travels the wire so
            # the worker's and engine's spans join this tree
            root = _obs.start_span(
                "srv_request", trace_id=_obs.new_trace_id(), rid=req.rid,
                slo=slo, tenant=req.tenant,
                prompt_tokens=int(prompt.size))
            req.trace_id = root.trace_id
            self._tspans[req.rid] = {"root": root}
            ta = time.perf_counter()
            self._admit(req)
            _obs.record_span("srv_admit", trace_id=root.trace_id,
                             parent_id=root.span_id,
                             dur_s=time.perf_counter() - ta,
                             outcome=req.status)
            if req.status == "queued":
                self._tspans[req.rid]["queue"] = _obs.start_span(
                    "srv_queue", trace_id=root.trace_id,
                    parent_id=root.span_id, slo=slo)
        else:
            self._admit(req)
        _obs.set_gauge("serving_router_queue_depth", self._queue_depth())
        return req.rid

    def _queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _admit(self, req: RouterRequest):
        if self._queue_depth() < self.config.queue_limit:
            self._queues[req.slo].append(req)
            return
        # full: preempt the youngest request of a strictly lower class,
        # else the incoming request itself is the lowest and is shed
        for cls in SLO_CLASSES:
            if cls == req.slo:
                break
            if self._queues[cls]:
                victim = self._queues[cls].pop()
                self._shed(victim, "queue_full")
                self._queues[req.slo].append(req)
                return
        self._shed(req, "queue_full")

    def _shed(self, req: RouterRequest, reason: str):
        req.status = "shed"
        req.shed_reason = reason
        req.finish_t = self._now()
        self.counters["shed"] += 1
        if self._acct is not None:
            self._acct.add(req.tenant, req.slo, shed_requests=1)
        _obs.inc("serving_router_shed_total")
        _obs.event("serving_router_shed", rid=req.rid, slo=req.slo,
                   tenant=req.tenant, reason=reason)
        t = self._tspans.pop(req.rid, None)
        if t:
            for k in ("queue", "retry"):
                if t.get(k):
                    _obs.end_span(t[k], outcome="shed")
            _obs.end_span(t["root"], status="shed", reason=reason)
        self._resolved(req)

    def _resolved(self, req: RouterRequest):
        """Terminal-transition tap: report the request to ``on_resolve``
        and, unless results are retained, drop it from the book so the
        request map stays bounded over million-request replays."""
        cb = self.on_resolve
        if cb is not None:
            cb(req)
        if not self.config.retain_results:
            self._requests.pop(req.rid, None)

    # -- fleet discovery & liveness -----------------------------------------

    def _discover(self):
        with deadline_guard("discover engines"):
            count = int(self._store.add(k_count(self._ns), 0))
        while self._known_engines < count:
            idx = self._known_engines
            key = k_engine(self._ns, idx)
            with deadline_guard("discover engines"):
                if not self._store.check(key):
                    return  # registration record not written yet; retry
                record = unpack(self._store.get(key))
            now = self._mono()
            est = _EngineState(name=record["name"], index=idx, record=record,
                               last_change=now, last_ack_t=now)
            if self._streaming and record.get("addr"):
                # fail-soft dial: a worker that listens but is not yet
                # polling still accepts (backlog); a dead addr backs off
                est.link = TransportClient(record["addr"],
                                           seed=self.config.seed)
            self._engines[est.name] = est
            self._by_index[idx] = est
            self._known_engines = idx + 1
            _obs.event("serving_router_engine_up", name=est.name, index=idx,
                       role=est.role)
            _obs.set_gauge("serving_router_engines", self._alive_count())

    def _alive_count(self) -> int:
        return sum(1 for e in self._engines.values() if e.alive)

    def _apply_occ(self, est: _EngineState, occ: dict, now: float):
        """Adopt an occupancy beat from EITHER source. Only an ADVANCING
        beat refreshes liveness — the slow store mirror lags the wire, and
        a stale copy must never resurrect a silent worker."""
        beat = int(occ.get("beat", -1))
        if beat <= est.beat:
            return
        est.beat = beat
        est.occ = occ
        est.acked_seq = int(occ.get("acked_seq", 0))
        est.last_change = now

    def _pump_wire(self):
        """Streaming dataplane intake: (re)introduce ourselves after
        (re)connects, then drain every worker connection — occupancy
        heartbeats, completion echoes, and prefill->decode relay
        notices."""
        if not self._streaming:
            return
        now = self._mono()
        for est in self._engines.values():
            link = est.link
            if link is None:
                continue
            if link.connected() and est.hello_sent != link.reconnects:
                if link.send({"t": "hello", "peer": "router",
                              "name": "router"}):
                    est.hello_sent = link.reconnects
            for frame in link.poll():
                t = frame.get("t")
                if t == "occ":
                    if est.alive:
                        self._apply_occ(est, frame.get("occ", {}), now)
                elif t == "done":
                    for rec in frame.get("recs", ()):
                        self._finish_from_wire(rec)
                elif t == "relay":
                    # the prefill engine handed these rids' KV pages to
                    # their decode engine; the decode side owns them now
                    for rid in frame.get("rids", ()):
                        est.inflight.pop(rid, None)
                elif t == "tele":
                    # live-telemetry batch riding the heartbeat; ingest
                    # dedups (src, seq) so the redundant re-sends and any
                    # locally tailed copies of the same spans collapse
                    if self._live_agg is not None:
                        for pay in frame.get("pays", ()):
                            self._live_agg.ingest(pay)

    def _read_occupancy(self):
        now = self._mono()
        if self._streaming:
            # the wire carries the hot beats; the store copy is only the
            # failover mirror and needs no more than the mirror cadence
            if now - self._last_occ_read < _STORE_MIRROR_S:
                return
        self._last_occ_read = now
        # one guard over the whole sweep: a guard arms a watchdog timer
        # (a thread), and per-engine guards made the mirror read cost
        # O(E) thread spawns per pump in the replay hot loop
        beats = []
        with deadline_guard("read occupancy"):
            for est in self._engines.values():
                if not est.alive:
                    continue
                key = k_occ(self._ns, est.name)
                if not self._store.check(key):
                    continue
                beats.append((est, unpack(self._store.get(key))))
        for est, occ in beats:
            self._apply_occ(est, occ, now)

    def _failover_dead(self):
        now = self._mono()
        for est in self._engines.values():
            if not est.alive:
                continue
            if now - est.last_change <= self.config.engine_grace_s:
                continue
            est.alive = False
            self.counters["engines_lost"] += 1
            _obs.event("serving_router_engine_dead", name=est.name,
                       inflight=len(est.inflight))
            _obs.set_gauge("serving_router_engines", self._alive_count())
            self._reassign_inflight(est, why="dead")

    def _reassign_inflight(self, est: _EngineState, why: str) -> int:
        """Harvest everything the engine already finished (done keys are
        written before the ack), then resubmit the rest to the FRONT of
        their class queues so failover does not add queueing delay on
        top of the rerun. Shared by dead-engine failover and supervisor
        drain-timeout evacuation; returns how many were resubmitted."""
        resubmit, finished = [], []
        with deadline_guard("harvest results"):
            for rid, req in list(est.inflight.items()):
                if self._store.check(k_done(self._ns, rid)):
                    finished.append(
                        (req, unpack(self._store.get(k_done(self._ns,
                                                            rid)))))
                else:
                    resubmit.append(req)
        for req, rec in finished:
            self._finish_with(req, rec)
        est.inflight.clear()
        for req in reversed(resubmit):
            # a disaggregated request dies with EITHER of its engines:
            # drop it from the partner's book too and rerun from
            # scratch (fresh prefill — bit-equal, the seed is explicit)
            self._resolve_inflight(req.rid)
            req.status = "queued"
            req.engine = None
            req.seq = -1
            req.kv_from = None
            req.wire_engine = None
            req.wire_rec = None
            req.resubmits += 1
            self._queues[req.slo].appendleft(req)
            self.counters["failover_resubmits"] += 1
            _obs.inc("serving_router_failover_total")
            _obs.event("serving_router_failover", rid=req.rid,
                       engine=est.name, slo=req.slo, why=why)
            t = self._tspans.get(req.rid)
            if t:
                # retry-flagged child under the SAME root: the window
                # from declared-dead through this request's redispatch
                t["retry"] = _obs.start_span(
                    "srv_retry", trace_id=t["root"].trace_id,
                    parent_id=t["root"].span_id, retry=True,
                    engine=est.name, resubmit=req.resubmits)
        return len(resubmit)

    def evacuate(self, name: str) -> int:
        """Hand a LIVE engine's in-flight requests off to the rest of
        the fleet — the fleet supervisor's drain-timeout escape hatch.
        The engine is not declared dead: it stays registered (its drain
        order already excludes it from placement). Reruns are bit-equal
        (router-assigned seeds), and a rid the drained engine still
        finishes is harmless — done records are keyed by rid, so the
        first finish wins and the duplicate write is identical."""
        est = self._engines.get(name)
        if est is None or not est.alive:
            return 0
        # adopt the drain locally: a wedged worker never refreshes its
        # occupancy beat, and waiting for one would re-place the
        # evacuated work right back on the engine being evacuated
        est.occ = dict(est.occ, draining=True)
        return self._reassign_inflight(est, why="evacuate")

    # -- results -------------------------------------------------------------

    def _resolve_inflight(self, rid: int):
        """Drop a rid from every engine's book (a disaggregated request
        is tracked on both its prefill and decode engine)."""
        for est in self._engines.values():
            est.inflight.pop(rid, None)

    def _finish_with(self, req: RouterRequest, rec: dict):
        self._resolve_inflight(req.rid)
        req.finish_t = self._now()
        if "error" in rec:
            req.status = "failed"
            req.error = rec["error"]
            self.counters["failed"] += 1
        else:
            req.status = "done"
            req.tokens = np.asarray(rec["tokens"], dtype=np.int64)
            self.counters["done"] += 1
            _obs.observe("serving_router_request_seconds",
                         req.finish_t - req.submit_t)
        t = self._tspans.pop(req.rid, None)
        if t:
            for k in ("queue", "retry"):
                if t.get(k):
                    _obs.end_span(t[k], engine=req.engine)
            _obs.end_span(t["root"], status=req.status, engine=req.engine,
                          resubmits=req.resubmits)
        self._resolved(req)

    def _finish_from_store(self, req: RouterRequest):
        with deadline_guard("harvest results"):
            rec = unpack(self._store.get(k_done(self._ns, req.rid)))
        self._finish_with(req, rec)

    def _finish_from_wire(self, rec: dict):
        """A ``done`` frame: trust it directly — the worker wrote the
        store key BEFORE sending it (done-before-ack), so acting on the
        echo can never outrun the ground truth. Late echoes for requests
        already resolved (or resubmitted after a failover) are dropped."""
        req = self._requests.get(rec.get("rid"))
        if req is None or req.status != "dispatched":
            return
        self._finish_with(req, rec)

    def _harvest_done(self):
        """Scan in-flight rids for finished store records, at most
        ``harvest_budget`` probes per pump. A rotating engine cursor
        resumes where the budget ran out, and an engine only commits its
        ``harvested_done`` watermark after a COMPLETE scan — a truncated
        one retries next pump, so bounding the work never strands a
        finished result."""
        names = [n for n, e in self._engines.items() if e.inflight]
        if not names:
            return
        budget = self.config.harvest_budget
        spent = 0
        start = self._harvest_cursor % len(names)
        for off in range(len(names)):
            est = self._engines[names[(start + off) % len(names)]]
            # only scan done keys when the engine's beat advertises new
            # completions: per-rid checks are store round trips, and with
            # deep inflight queues a blind every-pump scan contends the
            # store against the engines' own traffic
            reported = int(est.occ.get("done_count", -1))
            if reported >= 0 and reported == est.harvested_done:
                continue
            finished, complete = [], True
            with deadline_guard("harvest results"):
                for rid, req in list(est.inflight.items()):
                    if req.status != "dispatched":
                        est.inflight.pop(rid, None)
                        continue
                    if budget > 0 and spent >= budget:
                        complete = False
                        break
                    spent += 1
                    if self._store.check(k_done(self._ns, rid)):
                        finished.append(
                            (req, unpack(self._store.get(
                                k_done(self._ns, rid)))))
            for req, rec in finished:
                self._finish_with(req, rec)
            if complete:
                est.harvested_done = reported
            else:
                # budget exhausted mid-engine: resume HERE next pump
                self._harvest_cursor = (start + off) % len(names)
                return
        self._harvest_cursor = start + len(names)

    # -- placement -----------------------------------------------------------

    def _engine_cap(self, est: _EngineState) -> int:
        if self.config.max_inflight_per_engine > 0:
            return self.config.max_inflight_per_engine
        return 2 * int(est.record.get("num_slots", 1))

    def _load_tokens(self, est: _EngineState) -> int:
        """Outstanding tokens the engine reported, plus dispatched work it
        has not acked yet (seq >= acked_seq) so burst dispatches between
        beats don't all pile onto the same engine. A request whose KV
        pages are streaming in from a prefill engine counts until it
        finishes — the decode engine's own occupancy may not see it yet
        (deliberate over-estimate; it errs toward spreading load)."""
        load = int(est.occ.get("outstanding_tokens", 0))
        for req in est.inflight.values():
            cost = len(req.prompt) + req.params.max_new_tokens
            if req.kv_from is not None:
                if req.engine == est.name:
                    load += cost
            elif req.seq >= est.acked_seq:
                load += cost
        return load

    def _pick_engine(self, req: RouterRequest):
        """(decode-capable engine, via_affinity) or (None, False) when no
        capacity. Prefill-role workers never decode and are excluded."""
        candidates = [e for e in self._engines.values()
                      if e.alive and e.role != "prefill"
                      and not e.draining
                      and len(e.inflight) < self._engine_cap(e)]
        if not candidates:
            return None, False
        loads = {e.name: self._load_tokens(e) for e in candidates}
        best = min(candidates, key=lambda e: (loads[e.name], e.index))
        # deepest prompt block we have seen routed somewhere live wins,
        # unless honoring it would skew load past the slack
        for key in reversed(req.block_keys):
            name = self._affinity.get(key)
            if name is None:
                continue
            est = self._engines.get(name)
            if est is None or est not in candidates:
                break
            if loads[name] - loads[best.name] \
                    <= self.config.affinity_slack_tokens:
                return est, True
            break
        return best, False

    def _placement_ctx(self) -> dict:
        """Heap-mode placement book, built once per dispatch round: the
        load of every decode-capable candidate plus a min-heap ordered
        (load, engine index) — the scan's exact tie-break. Entries go
        stale as dispatches charge load; ``_pick_engine_heap`` discards
        them lazily, so each placement costs O(log E) instead of the
        scan's O(E·inflight) recompute."""
        loads: Dict[str, int] = {}
        entries: List[Tuple[int, int, str]] = []
        for e in self._engines.values():
            if (e.alive and e.role != "prefill" and not e.draining
                    and len(e.inflight) < self._engine_cap(e)):
                load = self._load_tokens(e)
                loads[e.name] = load
                entries.append((load, e.index, e.name))
        heapq.heapify(entries)
        return {"loads": loads, "heap": entries}

    def _pick_engine_heap(self, req: RouterRequest, ctx: dict):
        """Heap-mode twin of ``_pick_engine``: same contract, same
        placement (including the affinity override), different cost."""
        loads, heap = ctx["loads"], ctx["heap"]
        while heap:
            load, index, name = heap[0]
            if name not in loads:
                heapq.heappop(heap)  # hit its cap mid-round; evicted
                continue
            if load != loads[name]:
                heapq.heappop(heap)  # stale load; refresh lazily
                heapq.heappush(heap, (loads[name], index, name))
                continue
            break
        if not heap:
            return None, False
        best_load, _, best_name = heap[0]
        # deepest prompt block we have seen routed somewhere live wins,
        # unless honoring it would skew load past the slack
        for key in reversed(req.block_keys):
            name = self._affinity.get(key)
            if name is None:
                continue
            if name not in loads:
                break
            if loads[name] - best_load <= self.config.affinity_slack_tokens:
                return self._engines[name], True
            break
        return self._engines[best_name], False

    def _charge_placement(self, ctx: Optional[dict], est: _EngineState,
                          req: RouterRequest):
        """Book a dispatch against the round's placement state: bump the
        engine's load (push a fresh heap entry; the stale one dies
        lazily) or drop it from candidacy once it reaches its inflight
        cap — mirroring exactly what the scan would recompute."""
        if ctx is None:
            return
        loads = ctx["loads"]
        if est.name not in loads:
            return
        if len(est.inflight) >= self._engine_cap(est):
            del loads[est.name]
            return
        loads[est.name] += len(req.prompt) + req.params.max_new_tokens
        heapq.heappush(ctx["heap"],
                       (loads[est.name], est.index, est.name))

    def _prefill_load(self, est: _EngineState) -> int:
        """Prefill placement signal: reported queue depth + handoffs
        dispatched but not yet acked."""
        load = int(est.occ.get("prefill_queue", 0))
        load += sum(1 for r in est.inflight.values()
                    if r.kv_from == est.name and r.seq >= est.acked_seq)
        return load

    def _pick_prefill(self, req: RouterRequest) -> Optional[_EngineState]:
        """Shallowest-queue live prefill worker, or None (unified path).
        Only the streaming dataplane can carry the KV stream."""
        if (not self._streaming
                or len(req.prompt) < self.config.prefill_threshold_tokens):
            return None
        candidates = [e for e in self._engines.values()
                      if e.alive and e.role == "prefill"
                      and not e.draining
                      and len(e.inflight) < self._engine_cap(e)]
        if not candidates:
            return None
        return min(candidates,
                   key=lambda e: (self._prefill_load(e), e.index))

    def _build_rec(self, req: RouterRequest, est: _EngineState,
                   via_affinity: bool) -> dict:
        """Dispatch record on ``est``'s seq stream + the dispatch span.
        Shared by the unified and disaggregated paths."""
        req.seq = est.next_seq
        est.next_seq += 1
        # vars() not dataclasses.asdict(): SamplingParams is flat, and
        # asdict's recursive deep-copy walk is ~10x the cost — visible at
        # replay rates (a million dispatches per bench run)
        rec = {"rid": req.rid, "prompt": req.prompt.tolist(),
               "params": dict(vars(req.params))}
        if req.tenant != "-":
            # tenant + class ride the wire only when tagged: an untagged
            # request's dispatch record is byte-identical to before the
            # accounting plane existed (zero wire cost when unused)
            rec["tenant"] = req.tenant
            rec["slo"] = req.slo
        t = self._tspans.get(req.rid)
        if t:
            root = t["root"]
            for k in ("queue", "retry"):
                h = t.pop(k, None)
                if h:
                    _obs.end_span(h, engine=est.name)
            dh = _obs.start_span(
                "srv_dispatch", trace_id=root.trace_id,
                parent_id=root.span_id, engine=est.name, seq=req.seq,
                retry=req.resubmits > 0, affinity=via_affinity)
            # cross-process context: worker + engine continue this trace
            # (dispatch_ts is WALL clock — the worker closes the transit
            # span against it)
            rec["trace"] = {"trace_id": root.trace_id,
                            "parent_id": root.span_id,
                            "resubmits": req.resubmits,
                            "dispatch_ts": time.time()}
            _obs.end_span(dh)
        return rec

    def _enqueue_rec(self, est: _EngineState, rec: dict,
                     req: RouterRequest):
        """Hand the record to the dataplane via the engine's outbox —
        flushed once per engine per pump as a single wire frame, or (on
        the store path / a dead link) as one batched store-key write, so
        a dispatch burst costs one guard instead of one per record."""
        rec["seq"] = req.seq
        if self._streaming and est.link is not None:
            req.wire_engine = est.name
            req.wire_rec = rec
        est.outbox.append(rec)

    def _note_affinity(self, req: RouterRequest, name: str):
        for key in req.block_keys:
            self._affinity[key] = name
            self._affinity.move_to_end(key)
        while len(self._affinity) > _AFFINITY_CAP:
            self._affinity.popitem(last=False)

    def _dispatch_one(self, req: RouterRequest, est: _EngineState,
                      via_affinity: bool = False):
        rec = self._build_rec(req, est, via_affinity)
        self._enqueue_rec(est, rec, req)
        req.status = "dispatched"
        req.engine = est.name
        req.dispatch_t = self._now()
        est.inflight[req.rid] = req
        self.counters["dispatched"] += 1
        _obs.inc("serving_router_dispatch_total")
        self._note_affinity(req, est.name)

    def _dispatch_disagg(self, req: RouterRequest, pe: _EngineState,
                         de: _EngineState, via_affinity: bool):
        """Disaggregated placement: the record rides the PREFILL engine's
        seq stream and names the decode target (``kv_to``); the request is
        booked on both engines until the relay frame retires the prefill
        side. Affinity follows the decode engine — that is where the KV
        pages (and the registered prefix blocks) land."""
        rec = self._build_rec(req, pe, via_affinity)
        rec["kv_to"] = {"addr": de.addr, "name": de.name}
        req.kv_from = pe.name
        self._enqueue_rec(pe, rec, req)
        req.status = "dispatched"
        req.engine = de.name
        req.dispatch_t = self._now()
        pe.inflight[req.rid] = req
        de.inflight[req.rid] = req
        self.counters["dispatched"] += 1
        self.counters["disagg_dispatches"] += 1
        _obs.inc("serving_router_dispatch_total")
        self._note_affinity(req, de.name)

    def _dispatch(self):
        now = self._now()
        heap_mode = self.config.dispatch_mode == "heap"
        ctx = None  # built lazily on the first placement of the round
        for cls in reversed(SLO_CLASSES):  # interactive drains first
            queue = self._queues[cls]
            while queue:
                req = queue[0]
                if now > req.deadline_t:
                    queue.popleft()
                    self._shed(req, "deadline")
                    continue
                if heap_mode:
                    if ctx is None:
                        ctx = self._placement_ctx()
                    est, via_affinity = self._pick_engine_heap(req, ctx)
                else:
                    est, via_affinity = self._pick_engine(req)
                if est is None:
                    self._flush_outboxes()
                    return  # fleet saturated; lower classes wait too
                queue.popleft()
                if via_affinity:
                    self.counters["affinity_hits"] += 1
                    _obs.inc("serving_router_affinity_hits_total")
                pe = self._pick_prefill(req)
                if pe is not None:
                    self._dispatch_disagg(req, pe, est, via_affinity)
                else:
                    self._dispatch_one(req, est, via_affinity)
                self._charge_placement(ctx, est, req)
        self._flush_outboxes()
        _obs.set_gauge("serving_router_queue_depth", self._queue_depth())

    def _flush_outboxes(self):
        """One batched dispatch frame per engine per pump. The store
        dataplane (and a wire send that fails) writes the whole batch
        under ONE guard — the worker merges both sources by seq, so the
        fallback is ordering-safe and idempotent."""
        for est in self._engines.values():
            if not est.outbox:
                continue
            batch, est.outbox = est.outbox, []
            if self._streaming and est.link is not None and est.link.send(
                    {"t": "dispatch", "reqs": batch}):
                continue
            with deadline_guard("dispatch request"):
                for rec in batch:
                    self._store.set(k_req(self._ns, est.name, rec["seq"]),
                                    pack(rec))

    def _retransmit(self):
        """Re-send wire dispatches a worker has not acked within
        ``retransmit_s`` — and mirror them to the store, so even a
        half-open socket (sends 'succeed', peer never sees them) cannot
        wedge a request. Idempotent end to end: workers skip seqs below
        their consume cursor, and the store key for a consumed seq is
        never re-read."""
        if not self._streaming:
            return
        now = self._mono()
        for est in self._engines.values():
            if not est.alive:
                continue
            unacked = [r for r in est.inflight.values()
                       if r.wire_engine == est.name and r.wire_rec is not None
                       and r.seq >= est.acked_seq
                       and r.status == "dispatched"]
            if not unacked:
                est.last_ack_t = now
                continue
            if now - est.last_ack_t < self.config.retransmit_s:
                continue
            est.last_ack_t = now
            unacked.sort(key=lambda r: r.seq)
            recs = [r.wire_rec for r in unacked]
            self.counters["retransmits"] += len(recs)
            _obs.event("serving_router_retransmit", engine=est.name,
                       seqs=[r.seq for r in unacked])
            if est.link is not None:
                est.link.send({"t": "dispatch", "reqs": recs})
            with deadline_guard("dispatch request"):
                for rec in recs:
                    self._store.set(k_req(self._ns, est.name, rec["seq"]),
                                    pack(rec))

    # -- driving -------------------------------------------------------------

    def _export_load_gauges(self):
        """Per-engine outstanding-token and per-class admission-queue
        gauges — the placement signals, exported so the live plane (and
        any scraper) sees the same numbers the dispatcher acts on."""
        if not _obs.enabled():
            return
        for est in self._engines.values():
            if est.alive:
                _obs.set_gauge("serving_router_engine_outstanding_tokens",
                               self._load_tokens(est), engine=est.name)
        for cls, queue in self._queues.items():
            _obs.set_gauge("serving_router_admission_queue_length",
                           len(queue), slo=cls)

    def _live_tick(self):
        """Drive the live aggregator (lazily created so tests can flip
        the env per-case): hand it the queue depths, then let it poll
        local tails and write ``fleet_health.json`` at its own cadence.
        One env dict lookup per pump when the plane is off."""
        if self._live_agg is None:
            if not self._live_driver or not _live.live_enabled():
                return
            self._live_agg = _live.LiveAggregator()
        if self._live_driver:
            self._live_agg.note_queues({
                "admission": {c: len(q) for c, q in self._queues.items()},
                "engine_outstanding_tokens": {
                    e.name: self._load_tokens(e)
                    for e in self._engines.values() if e.alive},
            })
        if self._acct is not None:
            per_engine = self.tenant_outstanding()
            _acct.publish_outstanding(per_engine)
            # a non-driver leaf feeds only its ledger delta: the
            # frontier merges every leaf's outstanding map itself, and a
            # per-leaf overwrite here would clobber its siblings'
            self._live_agg.note_tenants(
                self._acct.collect_delta(),
                per_engine if self._live_driver else None)
        if self._live_driver:
            self._live_agg.tick()

    def tenant_outstanding(self) -> Dict[str, Dict[str, int]]:
        """Per-engine per-tenant outstanding tokens: the raw signal the
        quota ladder gates on (gauges set by accounting.py — single
        writer — and mirrored into fleet_health.json)."""
        per_engine: Dict[str, Dict[str, int]] = {}
        for est in self._engines.values():
            if not est.alive:
                continue
            for req in est.inflight.values():
                if req.status != "dispatched":
                    continue
                by = per_engine.setdefault(est.name, {})
                by[req.tenant] = by.get(req.tenant, 0) + len(
                    req.prompt) + req.params.max_new_tokens
        return per_engine

    def share_live_aggregator(self, agg: "_live.LiveAggregator"):
        """Adopt a live aggregator OWNED BY A FRONT TIER
        (serving/frontier.py). This leaf keeps feeding tenant deltas and
        ingesting wire telemetry into it, but stops writing queue gauges
        or driving the health-file tick — with several leaves in one
        process, two drivers would clobber each other's
        ``fleet_health.json`` view; the frontier merges and writes."""
        self._live_agg = agg
        self._live_driver = False

    def pump(self):
        """One scheduling round: discover new engines, drain the wire,
        refresh the store occupancy mirror, fail over dead workers,
        retransmit stalled dispatches, harvest finished results,
        dispatch."""
        self._discover()
        self._pump_wire()
        self._read_occupancy()
        self._failover_dead()
        self._retransmit()
        self._harvest_done()
        self._dispatch()
        _obs.set_gauge("serving_router_queue_depth", self._queue_depth())
        self._export_load_gauges()
        self._live_tick()

    def pending(self) -> int:
        """Requests admitted but not yet finished (queued + in flight)."""
        return sum(1 for r in self._requests.values()
                   if r.status in ("queued", "dispatched"))

    def queue_depth(self) -> int:
        """Admitted-but-undispatched requests across all SLO classes —
        the front tier's per-leaf placement signal."""
        return self._queue_depth()

    def admission_depths(self) -> Dict[str, int]:
        """Per-class admission queue depths (front-tier fleet view)."""
        return {c: len(q) for c, q in self._queues.items()}

    def drain(self, timeout: Optional[float] = None, poll: float = 0.005):
        """Pump until every admitted request resolves (done/failed/shed).
        Returns True on full drain, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.pending():
            self.pump()
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(poll)
        return True

    def shutdown(self):
        """Broadcast stop to every worker polling this namespace."""
        with deadline_guard("broadcast stop"):
            self._store.set(k_ctl(self._ns), pack({"stop": True}))
        for est in self._engines.values():
            if est.link is not None:
                est.link.close()

    # -- inspection ----------------------------------------------------------

    def status(self, rid: int) -> str:
        return self._requests[rid].status

    def result(self, rid: int) -> np.ndarray:
        req = self._requests[rid]
        if req.status == "done":
            return req.tokens
        if req.status == "shed":
            raise RuntimeError(
                f"request {rid} was shed ({req.shed_reason}); "
                f"slo={req.slo}")
        if req.status == "failed":
            raise RuntimeError(f"request {rid} failed on {req.engine}: "
                               f"{req.error}")
        raise RuntimeError(f"request {rid} not finished (status "
                           f"{req.status!r}); pump() the router")

    def latencies(self) -> Dict[str, List[float]]:
        """submit->finish seconds of completed requests, per SLO class."""
        out: Dict[str, List[float]] = {c: [] for c in SLO_CLASSES}
        for req in self._requests.values():
            if req.status == "done" and req.finish_t is not None:
                out[req.slo].append(req.finish_t - req.submit_t)
        return out

    def stats(self) -> dict:
        return {**self.counters,
                "queue_depth": self._queue_depth(),
                "engines_alive": self._alive_count(),
                "engines_known": self._known_engines}
