"""SLO-aware request router over a fleet of store-registered engines.

The router is the serving control plane: clients submit prompts tagged
with an SLO class, the router admits or sheds them against a bounded
queue, and each ``pump()`` round dispatches queued work to the live
engine fleet discovered through the coordination store. Placement is
least-outstanding-tokens — the engine-reported occupancy plus the load
this router dispatched but the engine has not yet acked — softened by
prefix affinity: a request whose chain-hashed prompt blocks were last
served by a particular engine routes back there (reusing that engine's
paged prefix cache) unless the load skew exceeds the affinity slack.

Overload policy: when the queue is full an incoming request preempts the
youngest request of a strictly lower SLO class, otherwise it is itself
shed. Shedding is always explicit — a counter, an event, and a
``RuntimeError`` from ``result`` naming the reason (queue_full or
deadline) — never a silent drop.

Failover: a worker whose occupancy beat stalls past the grace window is
declared dead. Its finished work is harvested from ``done`` keys (workers
write those before acking), and everything else is resubmitted to the
FRONT of its class queue. Reruns are bit-equal because the router stamps
every request with an explicit sampling seed at admission, so placement
is invisible in the token streams (no loss, no duplicates, no drift).

This module is the single writer of the ``serving_router_*`` telemetry
family (scripts/check_observability.py enforces that), and every store
call sits under ``protocol.deadline_guard`` (check_robustness.py rule 4).

Tracing: with telemetry enabled the router mints one trace per admitted
request and owns its router-side spans — ``srv_request`` (the root,
submit through result), ``srv_admit``, ``srv_queue``, ``srv_dispatch``
and ``srv_retry`` (failover resubmission windows, retry=True). The trace
context rides the ``__srv`` request record (protocol.py) so the worker
and engine continue the same tree; failover reruns attach under the same
root, never minting a second one.
"""
from __future__ import annotations

import time
from collections import OrderedDict, deque
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .. import observability as _obs
from ..inference.engine import PrefixRegistry, SamplingParams
from .protocol import (DEFAULT_DEADLINES, DEFAULT_NAMESPACE, SLO_CLASSES,
                       deadline_guard, k_ctl, k_done, k_engine, k_occ,
                       k_req, k_count, pack, unpack)

__all__ = ["Router", "RouterConfig", "RouterRequest"]

#: bound on the prefix-affinity LRU (block-key -> engine name entries)
_AFFINITY_CAP = 65536


@dataclass
class RouterConfig:
    namespace: str = DEFAULT_NAMESPACE
    #: total queued (not yet dispatched) requests across all SLO classes
    queue_limit: int = 64
    #: seconds from submit before a still-queued request is shed, per class
    deadlines: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_DEADLINES))
    #: occupancy beat staleness past which an engine is declared dead
    engine_grace_s: float = 5.0
    #: outstanding-token skew an affinity route may cost before the
    #: router abandons cache reuse for load balance
    affinity_slack_tokens: int = 512
    #: dispatched-but-unfinished requests allowed per engine
    #: (0 = twice the engine's slot count)
    max_inflight_per_engine: int = 0
    #: prompt block size for affinity chain hashes — match the engines'
    #: page_size or affinity keys never line up with their prefix caches
    page_size: int = 16
    #: base of the per-request sampling seeds the router assigns so
    #: reruns after failover are bit-equal on any engine
    seed: int = 0


@dataclass
class RouterRequest:
    rid: int
    prompt: np.ndarray
    params: SamplingParams
    slo: str
    submit_t: float
    deadline_t: float
    block_keys: List[bytes]
    status: str = "queued"  # queued | dispatched | done | failed | shed
    engine: Optional[str] = None
    seq: int = -1
    tokens: Optional[np.ndarray] = None
    error: Optional[str] = None
    shed_reason: Optional[str] = None
    finish_t: Optional[float] = None
    resubmits: int = 0
    trace_id: Optional[str] = None


@dataclass
class _EngineState:
    name: str
    index: int
    record: dict
    occ: dict = field(default_factory=dict)
    beat: int = -1
    acked_seq: int = 0
    next_seq: int = 0
    #: engine-reported completions already scanned for (-1 = never scanned)
    harvested_done: int = -1
    last_change: float = 0.0
    alive: bool = True
    #: rid -> RouterRequest, dispatch order (oldest first)
    inflight: "OrderedDict[int, RouterRequest]" = field(
        default_factory=OrderedDict)


class Router:
    """Admit, place, and track requests across the registered engines."""

    def __init__(self, store, config: Optional[RouterConfig] = None,
                 **overrides):
        if config is None:
            config = RouterConfig(**overrides)
        elif overrides:
            raise ValueError("pass config= or field overrides, not both")
        for cls in config.deadlines:
            if cls not in SLO_CLASSES:
                raise ValueError(f"unknown SLO class {cls!r}")
        self.config = config
        self._store = store
        self._ns = config.namespace
        self._engines: Dict[str, _EngineState] = {}
        self._by_index: Dict[int, _EngineState] = {}
        self._queues: Dict[str, deque] = {c: deque() for c in SLO_CLASSES}
        self._requests: Dict[int, RouterRequest] = {}
        self._affinity: "OrderedDict[bytes, str]" = OrderedDict()
        self._next_rid = 0
        self._known_engines = 0
        #: rid -> open span handles ("root", "queue", "retry"); entries
        #: exist only while telemetry is on and the request is unresolved
        self._tspans: Dict[int, dict] = {}
        self.counters = {"submitted": 0, "done": 0, "failed": 0, "shed": 0,
                         "dispatched": 0, "failover_resubmits": 0,
                         "affinity_hits": 0, "engines_lost": 0}

    # -- admission -----------------------------------------------------------

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               slo: str = "standard", **sampling) -> int:
        """Admit a request (or shed it under overload). Returns its rid;
        a shed request keeps the rid so ``status``/``result`` can report
        the rejection."""
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {slo!r}; expected one of {SLO_CLASSES}")
        if params is None:
            params = SamplingParams(**sampling)
        elif sampling:
            raise ValueError("pass params= or sampling kwargs, not both")
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        if params.seed is None:
            # explicit seed => bit-equal streams on ANY engine, which is
            # what makes failover reruns invisible in the results
            params = SamplingParams(**{**asdict(params),
                                       "seed": self.config.seed * 1_000_003
                                       + self._next_rid})
        now = time.perf_counter()
        req = RouterRequest(
            rid=self._next_rid, prompt=prompt, params=params, slo=slo,
            submit_t=now,
            deadline_t=now + self.config.deadlines.get(
                slo, DEFAULT_DEADLINES[slo]),
            block_keys=PrefixRegistry.block_keys(
                prompt, self.config.page_size))
        self._next_rid += 1
        self._requests[req.rid] = req
        self.counters["submitted"] += 1
        _obs.inc("serving_router_requests_total")
        if _obs.enabled():
            # one trace per admitted request; the id travels the wire so
            # the worker's and engine's spans join this tree
            root = _obs.start_span(
                "srv_request", trace_id=_obs.new_trace_id(), rid=req.rid,
                slo=slo, prompt_tokens=int(prompt.size))
            req.trace_id = root.trace_id
            self._tspans[req.rid] = {"root": root}
            ta = time.perf_counter()
            self._admit(req)
            _obs.record_span("srv_admit", trace_id=root.trace_id,
                             parent_id=root.span_id,
                             dur_s=time.perf_counter() - ta,
                             outcome=req.status)
            if req.status == "queued":
                self._tspans[req.rid]["queue"] = _obs.start_span(
                    "srv_queue", trace_id=root.trace_id,
                    parent_id=root.span_id, slo=slo)
        else:
            self._admit(req)
        _obs.set_gauge("serving_router_queue_depth", self._queue_depth())
        return req.rid

    def _queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _admit(self, req: RouterRequest):
        if self._queue_depth() < self.config.queue_limit:
            self._queues[req.slo].append(req)
            return
        # full: preempt the youngest request of a strictly lower class,
        # else the incoming request itself is the lowest and is shed
        for cls in SLO_CLASSES:
            if cls == req.slo:
                break
            if self._queues[cls]:
                victim = self._queues[cls].pop()
                self._shed(victim, "queue_full")
                self._queues[req.slo].append(req)
                return
        self._shed(req, "queue_full")

    def _shed(self, req: RouterRequest, reason: str):
        req.status = "shed"
        req.shed_reason = reason
        req.finish_t = time.perf_counter()
        self.counters["shed"] += 1
        _obs.inc("serving_router_shed_total")
        _obs.event("serving_router_shed", rid=req.rid, slo=req.slo,
                   reason=reason)
        t = self._tspans.pop(req.rid, None)
        if t:
            for k in ("queue", "retry"):
                if t.get(k):
                    _obs.end_span(t[k], outcome="shed")
            _obs.end_span(t["root"], status="shed", reason=reason)

    # -- fleet discovery & liveness -----------------------------------------

    def _discover(self):
        with deadline_guard("discover engines"):
            count = int(self._store.add(k_count(self._ns), 0))
        while self._known_engines < count:
            idx = self._known_engines
            key = k_engine(self._ns, idx)
            with deadline_guard("discover engines"):
                if not self._store.check(key):
                    return  # registration record not written yet; retry
                record = unpack(self._store.get(key))
            est = _EngineState(name=record["name"], index=idx, record=record,
                               last_change=time.monotonic())
            self._engines[est.name] = est
            self._by_index[idx] = est
            self._known_engines = idx + 1
            _obs.event("serving_router_engine_up", name=est.name, index=idx)
            _obs.set_gauge("serving_router_engines", self._alive_count())

    def _alive_count(self) -> int:
        return sum(1 for e in self._engines.values() if e.alive)

    def _read_occupancy(self):
        now = time.monotonic()
        for est in self._engines.values():
            if not est.alive:
                continue
            key = k_occ(self._ns, est.name)
            with deadline_guard("read occupancy"):
                if not self._store.check(key):
                    continue
                occ = unpack(self._store.get(key))
            if int(occ.get("beat", -1)) != est.beat:
                est.beat = int(occ.get("beat", -1))
                est.occ = occ
                est.acked_seq = int(occ.get("acked_seq", 0))
                est.last_change = now

    def _failover_dead(self):
        now = time.monotonic()
        for est in self._engines.values():
            if not est.alive:
                continue
            if now - est.last_change <= self.config.engine_grace_s:
                continue
            est.alive = False
            self.counters["engines_lost"] += 1
            _obs.event("serving_router_engine_dead", name=est.name,
                       inflight=len(est.inflight))
            _obs.set_gauge("serving_router_engines", self._alive_count())
            # harvest everything the dead engine already finished (done
            # keys are written before the ack), then resubmit the rest to
            # the FRONT of their class queues so failover does not add
            # queueing delay on top of the rerun
            resubmit = []
            for rid, req in est.inflight.items():
                with deadline_guard("harvest results"):
                    finished = self._store.check(k_done(self._ns, rid))
                if finished:
                    self._finish_from_store(req)
                else:
                    resubmit.append(req)
            est.inflight.clear()
            for req in reversed(resubmit):
                req.status = "queued"
                req.engine = None
                req.seq = -1
                req.resubmits += 1
                self._queues[req.slo].appendleft(req)
                self.counters["failover_resubmits"] += 1
                _obs.inc("serving_router_failover_total")
                _obs.event("serving_router_failover", rid=req.rid,
                           engine=est.name, slo=req.slo)
                t = self._tspans.get(req.rid)
                if t:
                    # retry-flagged child under the SAME root: the window
                    # from declared-dead through this request's redispatch
                    t["retry"] = _obs.start_span(
                        "srv_retry", trace_id=t["root"].trace_id,
                        parent_id=t["root"].span_id, retry=True,
                        engine=est.name, resubmit=req.resubmits)

    # -- results -------------------------------------------------------------

    def _finish_from_store(self, req: RouterRequest):
        with deadline_guard("harvest results"):
            rec = unpack(self._store.get(k_done(self._ns, req.rid)))
        req.finish_t = time.perf_counter()
        if "error" in rec:
            req.status = "failed"
            req.error = rec["error"]
            self.counters["failed"] += 1
        else:
            req.status = "done"
            req.tokens = np.asarray(rec["tokens"], dtype=np.int64)
            self.counters["done"] += 1
            _obs.observe("serving_router_request_seconds",
                         req.finish_t - req.submit_t)
        t = self._tspans.pop(req.rid, None)
        if t:
            for k in ("queue", "retry"):
                if t.get(k):
                    _obs.end_span(t[k], engine=req.engine)
            _obs.end_span(t["root"], status=req.status, engine=req.engine,
                          resubmits=req.resubmits)

    def _harvest_done(self):
        for est in self._engines.values():
            if not est.inflight:
                continue
            # only scan done keys when the engine's beat advertises new
            # completions: per-rid checks are store round trips, and with
            # deep inflight queues a blind every-pump scan contends the
            # store against the engines' own traffic
            reported = int(est.occ.get("done_count", -1))
            if reported >= 0 and reported == est.harvested_done:
                continue
            est.harvested_done = reported
            for rid, req in list(est.inflight.items()):
                with deadline_guard("harvest results"):
                    finished = self._store.check(k_done(self._ns, rid))
                if not finished:
                    continue
                self._finish_from_store(req)
                del est.inflight[rid]

    # -- placement -----------------------------------------------------------

    def _engine_cap(self, est: _EngineState) -> int:
        if self.config.max_inflight_per_engine > 0:
            return self.config.max_inflight_per_engine
        return 2 * int(est.record.get("num_slots", 1))

    def _load_tokens(self, est: _EngineState) -> int:
        """Outstanding tokens the engine reported, plus dispatched work it
        has not acked yet (seq >= acked_seq) so burst dispatches between
        beats don't all pile onto the same engine."""
        load = int(est.occ.get("outstanding_tokens", 0))
        for req in est.inflight.values():
            if req.seq >= est.acked_seq:
                load += len(req.prompt) + req.params.max_new_tokens
        return load

    def _pick_engine(self, req: RouterRequest):
        """(engine, via_affinity) or (None, False) when no capacity."""
        candidates = [e for e in self._engines.values()
                      if e.alive and len(e.inflight) < self._engine_cap(e)]
        if not candidates:
            return None, False
        loads = {e.name: self._load_tokens(e) for e in candidates}
        best = min(candidates, key=lambda e: (loads[e.name], e.index))
        # deepest prompt block we have seen routed somewhere live wins,
        # unless honoring it would skew load past the slack
        for key in reversed(req.block_keys):
            name = self._affinity.get(key)
            if name is None:
                continue
            est = self._engines.get(name)
            if est is None or est not in candidates:
                break
            if loads[name] - loads[best.name] \
                    <= self.config.affinity_slack_tokens:
                return est, True
            break
        return best, False

    def _dispatch_one(self, req: RouterRequest, est: _EngineState,
                      via_affinity: bool = False):
        req.seq = est.next_seq
        est.next_seq += 1
        rec = {"rid": req.rid, "prompt": req.prompt.tolist(),
               "params": asdict(req.params)}
        t = self._tspans.get(req.rid)
        dh = None
        if t:
            root = t["root"]
            for k in ("queue", "retry"):
                h = t.pop(k, None)
                if h:
                    _obs.end_span(h, engine=est.name)
            dh = _obs.start_span(
                "srv_dispatch", trace_id=root.trace_id,
                parent_id=root.span_id, engine=est.name, seq=req.seq,
                retry=req.resubmits > 0, affinity=via_affinity)
            # cross-process context: worker + engine continue this trace
            # (dispatch_ts is WALL clock — the worker closes the
            # srv_store_transit span against it)
            rec["trace"] = {"trace_id": root.trace_id,
                            "parent_id": root.span_id,
                            "resubmits": req.resubmits,
                            "dispatch_ts": time.time()}
        with deadline_guard("dispatch request"):
            self._store.set(k_req(self._ns, est.name, req.seq), pack(rec))
        if dh:
            _obs.end_span(dh)
        req.status = "dispatched"
        req.engine = est.name
        est.inflight[req.rid] = req
        self.counters["dispatched"] += 1
        _obs.inc("serving_router_dispatch_total")
        for key in req.block_keys:
            self._affinity[key] = est.name
            self._affinity.move_to_end(key)
        while len(self._affinity) > _AFFINITY_CAP:
            self._affinity.popitem(last=False)

    def _dispatch(self):
        now = time.perf_counter()
        for cls in reversed(SLO_CLASSES):  # interactive drains first
            queue = self._queues[cls]
            while queue:
                req = queue[0]
                if now > req.deadline_t:
                    queue.popleft()
                    self._shed(req, "deadline")
                    continue
                est, via_affinity = self._pick_engine(req)
                if est is None:
                    return  # fleet saturated; lower classes wait too
                queue.popleft()
                if via_affinity:
                    self.counters["affinity_hits"] += 1
                    _obs.inc("serving_router_affinity_hits_total")
                self._dispatch_one(req, est, via_affinity)
        _obs.set_gauge("serving_router_queue_depth", self._queue_depth())

    # -- driving -------------------------------------------------------------

    def pump(self):
        """One scheduling round: discover new engines, refresh occupancy,
        fail over dead workers, harvest finished results, dispatch."""
        self._discover()
        self._read_occupancy()
        self._failover_dead()
        self._harvest_done()
        self._dispatch()
        _obs.set_gauge("serving_router_queue_depth", self._queue_depth())

    def pending(self) -> int:
        """Requests admitted but not yet finished (queued + in flight)."""
        return sum(1 for r in self._requests.values()
                   if r.status in ("queued", "dispatched"))

    def drain(self, timeout: Optional[float] = None, poll: float = 0.005):
        """Pump until every admitted request resolves (done/failed/shed).
        Returns True on full drain, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.pending():
            self.pump()
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(poll)
        return True

    def shutdown(self):
        """Broadcast stop to every worker polling this namespace."""
        with deadline_guard("broadcast stop"):
            self._store.set(k_ctl(self._ns), pack({"stop": True}))

    # -- inspection ----------------------------------------------------------

    def status(self, rid: int) -> str:
        return self._requests[rid].status

    def result(self, rid: int) -> np.ndarray:
        req = self._requests[rid]
        if req.status == "done":
            return req.tokens
        if req.status == "shed":
            raise RuntimeError(
                f"request {rid} was shed ({req.shed_reason}); "
                f"slo={req.slo}")
        if req.status == "failed":
            raise RuntimeError(f"request {rid} failed on {req.engine}: "
                               f"{req.error}")
        raise RuntimeError(f"request {rid} not finished (status "
                           f"{req.status!r}); pump() the router")

    def latencies(self) -> Dict[str, List[float]]:
        """submit->finish seconds of completed requests, per SLO class."""
        out: Dict[str, List[float]] = {c: [] for c in SLO_CLASSES}
        for req in self._requests.values():
            if req.status == "done" and req.finish_t is not None:
                out[req.slo].append(req.finish_t - req.submit_t)
        return out

    def stats(self) -> dict:
        return {**self.counters,
                "queue_depth": self._queue_depth(),
                "engines_alive": self._alive_count(),
                "engines_known": self._known_engines}
