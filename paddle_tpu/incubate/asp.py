"""paddle.incubate.asp parity — automatic sparsity (2:4 structured pruning).

Reference: ``python/paddle/incubate/asp/`` (supported-layer registry,
magnitude-based 1-D/2-D n:m mask calculation, optimizer decoration that
re-applies masks after every step so pruned weights stay zero through
training). TPU-native: masks are device arrays; the decorated optimizer
multiplies masked params after its functional step — XLA fuses the mask
into the update program. (The reference's Ampere sparse-tensor-core
speedup has no TPU analogue; ASP here delivers the same MODEL sparsity
for compression/distillation workflows.)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.op import raw

_EXCLUDED: set = set()
_MASKS: Dict[int, object] = {}  # id(param) -> mask jnp array


def set_excluded_layers(param_names: List[str], main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def calculate_density(x) -> float:
    v = np.asarray(raw(x))
    return float((v != 0).sum() / v.size)


def _mask_2on4_1d(flat: np.ndarray) -> np.ndarray:
    """Keep the 2 largest-magnitude entries of every group of 4."""
    pad = (-len(flat)) % 4
    v = np.concatenate([flat, np.zeros(pad, flat.dtype)]).reshape(-1, 4)
    order = np.argsort(-np.abs(v), axis=1)
    mask = np.zeros_like(v, bool)
    np.put_along_axis(mask, order[:, :2], True, axis=1)
    return mask.reshape(-1)[: len(flat)]


def create_mask(x, func_name: str = "mask_2d_best", n: int = 2, m: int = 4):
    """n:m magnitude mask along the input dim (paddle asp semantics)."""
    v = np.asarray(raw(x))
    if n != 2 or m != 4:
        raise NotImplementedError("asp: only 2:4 masks are supported")
    flat = v.reshape(-1) if v.ndim == 1 else v
    if v.ndim == 1:
        return _mask_2on4_1d(flat).reshape(v.shape)
    rows = v.reshape(-1, v.shape[-1])
    mask = np.stack([_mask_2on4_1d(r) for r in rows])
    return mask.reshape(v.shape)


def check_sparsity(x, n: int = 2, m: int = 4) -> bool:
    v = np.asarray(raw(x)).reshape(-1)
    pad = (-len(v)) % m
    groups = np.concatenate([v, np.zeros(pad, v.dtype)]).reshape(-1, m)
    return bool(((groups != 0).sum(axis=1) <= n).all())


def _prunable(model):
    from ..nn import Conv2D, Linear

    for name, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, (Linear, Conv2D)) and hasattr(sub, "weight"):
            w = sub.weight
            if (w.name or name) in _EXCLUDED or name in _EXCLUDED:
                continue
            if raw(w).ndim >= 2 and raw(w).shape[-1] % 4 == 0:
                yield name, w


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_2d_best",
                with_mask: bool = True):
    """Apply 2:4 magnitude pruning to every supported layer; masks are
    remembered so a decorated optimizer keeps the pattern through training."""
    pruned = {}
    for name, w in _prunable(model):
        mask = jnp.asarray(create_mask(w, mask_algo, n, m), raw(w).dtype)
        w._rebind(raw(w) * mask)
        if with_mask:
            _MASKS[id(w)] = mask
        pruned[name] = float((np.asarray(mask) != 0).mean())
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step to re-apply the pruning masks after the update
    (the reference's OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step(*a, **k):
        out = orig_step(*a, **k)
        for p in optimizer._parameter_list:
            mask = _MASKS.get(id(p))
            if mask is not None:
                p._rebind(raw(p) * mask)
        return out

    optimizer.step = step
    optimizer._asp_decorated = True
    return optimizer
