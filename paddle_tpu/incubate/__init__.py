"""paddle.incubate parity namespace: MoE and experimental distributed models
(SURVEY.md §2.2 "Incubate")."""
from . import moe  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .moe import MoELayer, global_gather, global_scatter  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401


class distributed:  # paddle.incubate.distributed.* path parity
    class models:
        from . import moe

    # paddle.incubate.distributed.fleet: the reference's incubate fleet
    # utilities live in the same module tree as fleet proper here; resolved
    # lazily to avoid an import cycle with paddle_tpu.distributed
    class _FleetProxy:
        def __getattr__(self, name):
            from ..distributed import fleet as _f

            return getattr(_f, name)

    fleet = _FleetProxy()


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Legacy name for geometric.send_u_recv (reference:
    python/paddle/incubate/operators/graph_send_recv.py)."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def segment_sum(data, segment_ids, name=None):
    from ..geometric import segment_sum as _s

    return _s(data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    from ..geometric import segment_mean as _s

    return _s(data, segment_ids)


def segment_max(data, segment_ids, name=None):
    from ..geometric import segment_max as _s

    return _s(data, segment_ids)


def segment_min(data, segment_ids, name=None):
    from ..geometric import segment_min as _s

    return _s(data, segment_ids)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) over the last axis (reference:
    incubate/operators/softmax_mask_fuse.py — a hand-written CUDA fusion;
    XLA fuses the add into the softmax natively, so one defop suffices)."""
    return _softmax_mask_fuse_op(x, mask)


from ..framework.op import defop as _defop  # noqa: E402


@_defop(name="softmax_mask_fuse_op")
def _softmax_mask_fuse_op(x, mask):
    import jax

    return jax.nn.softmax(x + mask, axis=-1)


def identity_loss(x, reduction="none"):
    """Mark a loss for IPU-style identity handling (reference:
    incubate/autograd): reduce per `reduction` and return it unchanged."""
    if reduction in ("mean", 1):
        return x.mean()
    if reduction in ("sum", 0):
        return x.sum()
    return x


from .. import autograd  # noqa: E402,F401  (paddle.incubate.autograd parity:
# jvp/vjp/Jacobian/Hessian live on the main autograd module)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, **kw):
    """Multi-hop sampling (legacy incubate name): iterate geometric
    sample_neighbors per hop."""
    from ..geometric import sample_neighbors

    nodes = input_nodes
    all_nb, all_cnt = [], []
    for k in sample_sizes:
        nb, cnt = sample_neighbors(row, colptr, nodes, sample_size=int(k))
        all_nb.append(nb)
        all_cnt.append(cnt)
        nodes = nb
    return all_nb, all_cnt


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1, **kw):
    from ..geometric import sample_neighbors

    return sample_neighbors(row, colptr, input_nodes, sample_size)


def graph_reindex(x, neighbors, count, **kw):
    from ..geometric import reindex_graph

    return reindex_graph(x, neighbors, count)


from . import asp  # noqa: E402,F401


import jax.numpy as jnp  # noqa: E402


def softmax_mask_fuse_upper_triangle(x):
    """softmax over the last axis with the upper triangle masked (causal) —
    reference: incubate/operators/softmax_mask_fuse_upper_triangle.py (a
    fused CUDA kernel for GPT attention); XLA fuses the where+softmax."""
    return _softmax_mask_fuse_upper_triangle_op(x)


@_defop(name="softmax_mask_fuse_upper_triangle_op")
def _softmax_mask_fuse_upper_triangle_op(x):
    import jax

    t_q, t_k = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((t_q, t_k), bool), t_k - t_q)
    masked = jnp.where(causal, x, jnp.asarray(-1e4, x.dtype))
    return jax.nn.softmax(masked.astype(jnp.float32), axis=-1).astype(x.dtype)
