"""paddle.incubate parity namespace: MoE and experimental distributed models
(SURVEY.md §2.2 "Incubate")."""
from . import moe  # noqa: F401
from . import nn  # noqa: F401
from .moe import MoELayer, global_gather, global_scatter  # noqa: F401


class distributed:  # paddle.incubate.distributed.models.moe path parity
    class models:
        from . import moe
