"""paddle.incubate.nn parity — fused transformer building blocks.

Reference: ``python/paddle/incubate/nn/layer/fused_transformer.py``
(FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer —
hand-fused CUDA kernels). TPU-native design: "fused" here means the whole
block is expressed as a few large jnp ops (qkv as ONE matmul, flash
attention via the Pallas kernel on TPU, bias+residual+layernorm left to XLA
fusion) — the compiler produces the fusion the reference hand-writes.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.op import raw
from ..nn import Dropout, LayerNorm
from ..nn import functional as F
from ..nn.layer import Layer


class FusedMultiHeadAttention(Layer):
    def __init__(
        self,
        embed_dim: int,
        num_heads: int,
        dropout_rate: float = 0.5,
        attn_dropout_rate: float = 0.5,
        kdim=None,
        vdim=None,
        normalize_before: bool = False,
        need_weights: bool = False,
        qkv_weight_attr=None,
        qkv_bias_attr=None,
        linear_weight_attr=None,
        linear_bias_attr=None,
        pre_ln_scale_attr=None,
        pre_ln_bias_attr=None,
        ln_scale_attr=None,
        ln_bias_attr=None,
        epsilon: float = 1e-5,
        nranks: int = 1,
        ring_id: int = -1,
    ):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"num_heads ({num_heads}) must divide embed_dim ({embed_dim})"
            )
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        # one fused qkv projection (the reference's qkv_weight [3, H, D, E])
        self.qkv_weight = self.create_parameter((embed_dim, 3 * embed_dim), attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter((3 * embed_dim,), attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter((embed_dim, embed_dim), attr=linear_weight_attr)
        self.linear_bias = self.create_parameter((embed_dim,), attr=linear_bias_attr, is_bias=True)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        # all math goes through framework ops so the eager autograd tape
        # records it (raw jnp math here would silently detach gradients)
        from ..tensor import manipulation as M

        residual = x
        if self.normalize_before:
            x = self.ln(x)
        B, T, E = x.shape
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)  # [B, T, 3E]
        q, k, v = M.split(qkv, 3, axis=-1)
        q = M.reshape(q, [B, T, self.num_heads, self.head_dim])
        k = M.reshape(k, [B, T, self.num_heads, self.head_dim])
        v = M.reshape(v, [B, T, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            q, k, v,
            attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
        )
        out = F.linear(M.reshape(out, [B, T, E]), self.linear_weight, self.linear_bias)
        out = self.dropout(out) + residual
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(
        self,
        d_model: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        epsilon: float = 1e-5,
        activation: str = "relu",
        act_dropout_rate: Optional[float] = None,
        normalize_before: bool = False,
        linear1_weight_attr=None,
        linear1_bias_attr=None,
        linear2_weight_attr=None,
        linear2_bias_attr=None,
        ln1_scale_attr=None,
        ln1_bias_attr=None,
        nranks: int = 1,
        ring_id: int = -1,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        self.w1 = self.create_parameter((d_model, dim_feedforward), attr=linear1_weight_attr)
        self.b1 = self.create_parameter((dim_feedforward,), attr=linear1_bias_attr, is_bias=True)
        self.w2 = self.create_parameter((dim_feedforward, d_model), attr=linear2_weight_attr)
        self.b2 = self.create_parameter((d_model,), attr=linear2_bias_attr, is_bias=True)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.activation = activation

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        act = getattr(F, self.activation)
        h = self.act_dropout(act(F.linear(x, self.w1, self.b1)))
        out = self.dropout(F.linear(h, self.w2, self.b2)) + residual
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model: int,
        nhead: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        activation: str = "relu",
        attn_dropout_rate: Optional[float] = None,
        act_dropout_rate: Optional[float] = None,
        normalize_before: bool = False,
    ):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward,
            dropout_rate=dropout_rate,
            act_dropout_rate=act_dropout_rate,
            activation=activation,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedLinear(Layer):
    """paddle.incubate.nn.FusedLinear — on TPU a plain Linear already fuses
    matmul+bias in XLA; provided for API parity."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, transpose_weight=False):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in_features) if transpose_weight else (in_features, out_features),
            attr=weight_attr,
        )
        self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)
        self._transpose = transpose_weight

    def forward(self, x):
        from ..tensor import manipulation as M

        w = self.weight
        if self._transpose:
            w = M.transpose(w, [1, 0])
        return F.linear(x, w, self.bias)


__all__ = [
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedLinear",
]
