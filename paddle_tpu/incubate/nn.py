"""paddle.incubate.nn parity — fused transformer building blocks.

Reference: ``python/paddle/incubate/nn/layer/fused_transformer.py``
(FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer —
hand-fused CUDA kernels). TPU-native design: "fused" here means the whole
block is expressed as a few large jnp ops (qkv as ONE matmul, flash
attention via the Pallas kernel on TPU, bias+residual+layernorm left to XLA
fusion) — the compiler produces the fusion the reference hand-writes.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.op import raw
from ..nn import Dropout, LayerNorm
from ..nn import functional as F
from ..nn.layer import Layer


class FusedMultiHeadAttention(Layer):
    def __init__(
        self,
        embed_dim: int,
        num_heads: int,
        dropout_rate: float = 0.5,
        attn_dropout_rate: float = 0.5,
        kdim=None,
        vdim=None,
        normalize_before: bool = False,
        need_weights: bool = False,
        qkv_weight_attr=None,
        qkv_bias_attr=None,
        linear_weight_attr=None,
        linear_bias_attr=None,
        pre_ln_scale_attr=None,
        pre_ln_bias_attr=None,
        ln_scale_attr=None,
        ln_bias_attr=None,
        epsilon: float = 1e-5,
        nranks: int = 1,
        ring_id: int = -1,
    ):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(
                f"num_heads ({num_heads}) must divide embed_dim ({embed_dim})"
            )
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        # one fused qkv projection (the reference's qkv_weight [3, H, D, E])
        self.qkv_weight = self.create_parameter((embed_dim, 3 * embed_dim), attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter((3 * embed_dim,), attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter((embed_dim, embed_dim), attr=linear_weight_attr)
        self.linear_bias = self.create_parameter((embed_dim,), attr=linear_bias_attr, is_bias=True)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        # all math goes through framework ops so the eager autograd tape
        # records it (raw jnp math here would silently detach gradients)
        from ..tensor import manipulation as M

        residual = x
        if self.normalize_before:
            x = self.ln(x)
        B, T, E = x.shape
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)  # [B, T, 3E]
        q, k, v = M.split(qkv, 3, axis=-1)
        q = M.reshape(q, [B, T, self.num_heads, self.head_dim])
        k = M.reshape(k, [B, T, self.num_heads, self.head_dim])
        v = M.reshape(v, [B, T, self.num_heads, self.head_dim])
        out = F.scaled_dot_product_attention(
            q, k, v,
            attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
        )
        out = F.linear(M.reshape(out, [B, T, E]), self.linear_weight, self.linear_bias)
        out = self.dropout(out) + residual
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(
        self,
        d_model: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        epsilon: float = 1e-5,
        activation: str = "relu",
        act_dropout_rate: Optional[float] = None,
        normalize_before: bool = False,
        linear1_weight_attr=None,
        linear1_bias_attr=None,
        linear2_weight_attr=None,
        linear2_bias_attr=None,
        ln1_scale_attr=None,
        ln1_bias_attr=None,
        nranks: int = 1,
        ring_id: int = -1,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        self.w1 = self.create_parameter((d_model, dim_feedforward), attr=linear1_weight_attr)
        self.b1 = self.create_parameter((dim_feedforward,), attr=linear1_bias_attr, is_bias=True)
        self.w2 = self.create_parameter((dim_feedforward, d_model), attr=linear2_weight_attr)
        self.b2 = self.create_parameter((d_model,), attr=linear2_bias_attr, is_bias=True)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.activation = activation

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        act = getattr(F, self.activation)
        h = self.act_dropout(act(F.linear(x, self.w1, self.b1)))
        out = self.dropout(F.linear(h, self.w2, self.b2)) + residual
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model: int,
        nhead: int,
        dim_feedforward: int,
        dropout_rate: float = 0.1,
        activation: str = "relu",
        attn_dropout_rate: Optional[float] = None,
        act_dropout_rate: Optional[float] = None,
        normalize_before: bool = False,
    ):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward,
            dropout_rate=dropout_rate,
            act_dropout_rate=act_dropout_rate,
            activation=activation,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """Stack of fused decoder blocks for generation serving (reference:
    ``python/paddle/incubate/nn/layer/fused_transformer.py::FusedMultiTransformer``
    — the multi-layer CUDA kernel behind PaddleNLP's LLM inference).

    TPU-native design: each layer is pre-norm attention + FFN expressed as
    large jnp ops (qkv packed as one matmul); incremental decoding uses the
    ``caches`` argument — a list of (k, v) arrays per layer, matching the
    reference's CacheKV — with ``time_step`` selecting the write position
    (static-shape update, serving-loop friendly).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-layernorm only (as the "
                "reference kernel)")
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self._act = activation
        self.layers = []
        for i in range(num_layers):
            blk = {
                "ln": LayerNorm(embed_dim),
                "qkv": FusedLinear(embed_dim, 3 * embed_dim),
                "out_proj": FusedLinear(embed_dim, embed_dim),
                "ffn_ln": LayerNorm(embed_dim),
                "ffn1": FusedLinear(embed_dim, dim_feedforward),
                "ffn2": FusedLinear(dim_feedforward, embed_dim),
            }
            for k, sub in blk.items():
                self.add_sublayer(f"layer{i}_{k}", sub)
            self.layers.append(blk)
        self.dropout = Dropout(dropout_rate)

    def _attn(self, blk, x, attn_mask, cache, time_step):
        b, s, _ = x.shape
        h, hd = self.num_heads, self.head_dim
        qkv = raw(blk["qkv"](blk["ln"](x)))  # [b, s, 3e]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, h, hd)
        k = k.reshape(b, s, h, hd)
        v = v.reshape(b, s, h, hd)
        if cache is not None:
            ck, cv = cache  # [b, max_len, h, hd]
            if time_step is not None:
                t = raw(time_step) if isinstance(time_step, Tensor) else time_step
                t = t if hasattr(t, "shape") else int(t)
                import jax as _jax

                # write ALL s tokens at [t, t+s) (chunked/speculative decode;
                # s=1 is the common serving step)
                ck = _jax.lax.dynamic_update_slice_in_dim(
                    jnp.asarray(ck), k, t, axis=1)
                cv = _jax.lax.dynamic_update_slice_in_dim(
                    jnp.asarray(cv), v, t, axis=1)
                k, v = ck, cv
                # query i (absolute position t+i) sees cache slots j <= t+i
                valid = (jnp.arange(k.shape[1])[None, :]
                         <= (t + jnp.arange(s))[:, None])[None, None]
            else:  # prefill: write the prompt into the cache head
                ck = jnp.asarray(ck).at[:, :s].set(k)
                cv = jnp.asarray(cv).at[:, :s].set(v)
                k, v = ck, cv
                # causal within the prompt; slots >= s are empty (j <= i < s)
                valid = (jnp.arange(k.shape[1])[None, :]
                         <= jnp.arange(s)[:, None])[None, None]
            if attn_mask is None:
                attn_mask = Tensor(valid)
            else:
                m = raw(attn_mask) if isinstance(attn_mask, Tensor) else jnp.asarray(attn_mask)
                L = k.shape[1]
                if m.shape[-1] != L:
                    # reference-shaped prompt mask [.., s, s]: pad the key
                    # axis to cache length (the tail is already invalidated
                    # by `valid`, so the pad value is inert)
                    pad = [(0, 0)] * (m.ndim - 1) + [(0, L - m.shape[-1])]
                    m = jnp.pad(m, pad, constant_values=(
                        True if m.dtype == jnp.bool_ else 0.0))
                if m.dtype == jnp.bool_:
                    attn_mask = Tensor(m & valid)
                else:
                    neg = jnp.asarray(jnp.finfo(m.dtype).min, m.dtype)
                    attn_mask = Tensor(jnp.where(valid, m, neg))
            new_cache = (k, v)
        else:
            new_cache = None
        out = F.scaled_dot_product_attention(
            Tensor(q), Tensor(k), Tensor(v),
            attn_mask=attn_mask,
            is_causal=(attn_mask is None and cache is None),
        )
        out = raw(out).reshape(b, s, h * hd)
        return raw(blk["out_proj"](Tensor(out))), new_cache

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        x = raw(src) if isinstance(src, Tensor) else jnp.asarray(src)
        new_caches = []
        act = getattr(F, self._act)
        for i, blk in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            attn_out, new_cache = self._attn(blk, Tensor(x), attn_mask, cache,
                                             time_step)
            if new_cache is not None:
                new_caches.append(new_cache)
            x = x + raw(self.dropout(Tensor(attn_out)))
            ffn_in = blk["ffn_ln"](Tensor(x))
            ffn = blk["ffn2"](act(blk["ffn1"](ffn_in)))
            x = x + raw(self.dropout(ffn))
        out = Tensor(x)
        if caches is not None:
            return out, new_caches
        return out


class FusedLinear(Layer):
    """paddle.incubate.nn.FusedLinear — on TPU a plain Linear already fuses
    matmul+bias in XLA; provided for API parity."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, transpose_weight=False):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in_features) if transpose_weight else (in_features, out_features),
            attr=weight_attr,
        )
        self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)
        self._transpose = transpose_weight

    def forward(self, x):
        from ..tensor import manipulation as M

        w = self.weight
        if self._transpose:
            w = M.transpose(w, [1, 0])
        return F.linear(x, w, self.bias)


__all__ = [
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer", "FusedLinear",
]


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None, name=None):
    """Functional fused MHA (reference: incubate.nn.functional.
    fused_multi_head_attention). XLA fuses the chain; the functional form
    exists for script parity. qkv_weight: [3, H, D/H, D] paddle layout.

    Everything flows through framework ops (F.linear / Tensor methods) so
    the eager tape records the whole chain — raw jnp math here would
    silently detach gradients (see the fused-layer comment above).
    """
    from ..nn import functional as F

    if cache_kv is not None:
        raise NotImplementedError(
            "cache_kv (incremental decoding) is not supported by the fused "
            "functional; use nn.MultiHeadAttention with its cache API"
        )
    three, nh, hd, d = qkv_weight.shape
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, [d], weight=pre_ln_scale, bias=pre_ln_bias,
                         epsilon=pre_ln_epsilon)
    w = qkv_weight.reshape([3 * nh * hd, d]).t()  # [d, 3*nh*hd], tape op
    b_ = qkv_bias.reshape([3 * nh * hd]) if qkv_bias is not None else None
    qkv = F.linear(h, w, b_)
    from .. import tensor as _pt

    q, k, v = _pt.split(qkv, 3, axis=-1)
    b, t = h.shape[0], h.shape[1]
    r = lambda a: a.reshape([b, t, nh, hd])
    o = F.scaled_dot_product_attention(
        r(q), r(k), r(v), attn_mask=attn_mask,
        dropout_p=attn_dropout_rate, training=training,
    )
    out = F.linear(o.reshape([b, t, nh * hd]), linear_weight, linear_bias)
    if dropout_rate:
        out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = out + x
    if not pre_layer_norm:
        out = F.layer_norm(out, [d], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, add_residual=True,
                      name=None):
    """Functional fused FFN (reference: incubate.nn.functional.fused_feedforward)."""
    from ..nn import functional as F

    d = x.shape[-1]
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, [d], weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = F.linear(h, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate:
        h = F.dropout(h, dropout1_rate, training=training, mode=mode)
    h = F.linear(h, linear2_weight, linear2_bias)
    if dropout2_rate:
        h = F.dropout(h, dropout2_rate, training=training, mode=mode)
    if add_residual:
        h = h + x
    if not pre_layer_norm:
        h = F.layer_norm(h, [d], weight=ln2_scale, bias=ln2_bias,
                         epsilon=ln2_epsilon)
    return h


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    """incubate.nn.functional.fused_rms_norm parity — rides F.rms_norm
    (XLA fuses the reduce+scale chain)."""
    from ..nn import functional as F

    nd = len(x.shape)
    axis = begin_norm_axis % nd if begin_norm_axis >= 0 else begin_norm_axis % nd
    if axis != nd - 1:
        raise NotImplementedError(
            "fused_rms_norm: only last-axis normalization is supported "
            f"(begin_norm_axis={begin_norm_axis} on rank-{nd} input)"
        )
    out = F.rms_norm(x, norm_weight, epsilon=epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     residual_alpha=1.0, begin_norm_axis=1, bias=None,
                     residual=None, quant_scale=-1, name=None, **_quant_kw):
    """incubate.nn.functional.fused_layer_norm parity: LN(x + bias +
    residual_alpha * residual). Quantized outputs (quant_scale > 0) are
    not supported. Returns (out, residual_out) when a residual is given
    (reference contract), else out."""
    from ..nn import functional as F

    if quant_scale > 0:
        raise NotImplementedError("fused_layer_norm: quantized output path")
    import numpy as _np

    from ..tensor import manipulation as M

    h = x
    if bias is not None:
        h = h + bias
    if residual is not None:
        h = h + residual_alpha * residual
    nd = len(h.shape)
    axis = begin_norm_axis % nd
    shape = list(h.shape[axis:])
    # the reference takes FLAT 1-D weight/bias of size prod(shape); reshape
    # to the normalized dims (and validate) before the broadcasting norm
    want = int(_np.prod(shape))

    def _fit(t, what):
        if t is None:
            return None
        size = int(_np.prod(t.shape))
        if size != want:
            raise ValueError(
                f"fused_layer_norm: {what} has {size} elements but "
                f"normalization over dims {shape} needs {want}"
            )
        return M.reshape(t, shape) if list(t.shape) != shape else t

    out = F.layer_norm(h, shape, weight=_fit(norm_weight, "norm_weight"),
                       bias=_fit(norm_bias, "norm_bias"), epsilon=epsilon)
    return (out, h) if residual is not None else out


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """incubate.nn.functional.fused_bias_dropout_residual_layer_norm
    parity: LN(residual + dropout(x + bias)) — one fused region under
    XLA."""
    from ..nn import functional as F

    h = x if bias is None else x + bias
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    h = residual + h
    d = h.shape[-1]
    return F.layer_norm(h, [d], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """incubate.nn.functional.fused_linear parity (one matmul+bias-add
    region; the reference fuses via cublasLt, XLA fuses natively)."""
    from .. import matmul

    y = matmul(x, weight, transpose_y=transpose_weight)
    return y if bias is None else y + bias


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """incubate.nn.functional.fused_linear_activation parity:
    act(x @ y + bias) with act in {gelu, relu, none}."""
    from .. import matmul
    from ..nn import functional as F

    out = matmul(x, y, transpose_x=trans_x, transpose_y=trans_y) + bias
    if activation == "gelu":
        return F.gelu(out)
    if activation == "relu":
        return F.relu(out)
    if activation in (None, "", "none", "identity"):
        return out
    raise ValueError(f"fused_linear_activation: unknown activation {activation!r}")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    name=None):
    """incubate.nn.functional.fused_rotary_position_embedding parity.

    q/k/v: [B, T, H, D]; sin/cos: [1, T, 1, D], [T, D] duplicated, or
    [T, D/2] half-dim caches. Rotates every provided input (the reference
    rotates v too). position_ids and non-neox pairing are not implemented
    and raise rather than silently mis-rotating.
    """
    from ..text.models.llama import _apply_rope, _rope_cache
    from ..framework.op import raw
    import jax.numpy as jnp

    if position_ids is not None:
        raise NotImplementedError(
            "fused_rotary_position_embedding: position_ids offsets are not "
            "supported; slice the sin/cos caches instead"
        )
    if not use_neox_rotary_style:
        raise NotImplementedError(
            "fused_rotary_position_embedding: interleaved (non-neox) pairing "
            "is not supported"
        )
    d = q.shape[-1]
    if cos is None or sin is None:
        c_np, s_np = _rope_cache(q.shape[1], d, 10000.0)
        cos_h, sin_h = jnp.asarray(c_np), jnp.asarray(s_np)
    else:
        cos_v, sin_v = jnp.asarray(raw(cos)), jnp.asarray(raw(sin))
        cos_v = cos_v.reshape(-1, cos_v.shape[-1])  # [T, D] or [T, D/2]
        sin_v = sin_v.reshape(-1, sin_v.shape[-1])
        # accept full-dim duplicated caches ([T, D]) or half-dim ([T, D/2])
        cos_h = cos_v[:, : d // 2] if cos_v.shape[-1] == d else cos_v
        sin_h = sin_v[:, : d // 2] if sin_v.shape[-1] == d else sin_v
    rot = lambda t: _apply_rope(t, cos_h, sin_h) if t is not None else None
    return rot(q), rot(k), rot(v)


class FusedDropoutAdd(Layer):
    """dropout(x) + y in one op (paddle.incubate.nn.FusedDropoutAdd — a CUDA
    fusion upstream; XLA fuses the same pattern, so this is the composition
    with the fused intent documented)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode
        self._drop = Dropout(p, mode=mode)

    def forward(self, x, y):
        return self._drop(x) + y


class FusedEcMoe(Layer):
    """Expert-choice MoE layer (paddle.incubate.nn.FusedEcMoe): EXPERTS pick
    their top-C tokens (capacity-perfect, no token dropping decisions), via
    one batched einsum pair per projection — MXU-shaped, no gather loops."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I

        self.num_experts = num_experts
        self.hidden_size = hidden_size
        self.act = getattr(F, act_type)
        self.gate = self.create_parameter(
            [hidden_size, num_experts], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.w1 = self.create_parameter(
            [num_experts, hidden_size, inter_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter(
            [num_experts, 1, inter_size], attr=bias_attr, is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, inter_size, hidden_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter(
            [num_experts, 1, hidden_size], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return _fused_ec_moe(x, self.gate, self.w1, self.b1, self.w2, self.b2,
                             act=self.act.__name__ if hasattr(self.act, "__name__") else "gelu",
                             num_experts=self.num_experts)


from ..framework.op import defop as _defop  # noqa: E402


@_defop(name="fused_ec_moe_op")
def _fused_ec_moe(x, gate, w1, b1, w2, b2, act, num_experts):
    import jax

    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    n_tok = b * s
    cap = max(n_tok // num_experts, 1)
    if gate.ndim == 3:
        # functional form: precomputed gate LOGITS [b, s, E] (the layer
        # passes its [d, E] gate weight instead)
        scores = jax.nn.softmax(gate.reshape(n_tok, -1), axis=-1)
    else:
        scores = jax.nn.softmax(tokens @ gate, axis=-1)  # [T, E]
    # expert choice: each expert takes its top-cap tokens by score
    g, idx = jax.lax.top_k(scores.T, cap)  # [E, cap]
    picked = jnp.take(tokens, idx.reshape(-1), axis=0).reshape(
        num_experts, cap, d)
    act_fn = getattr(jax.nn, act, jax.nn.gelu)
    h = act_fn(jnp.einsum("ecd,edf->ecf", picked, w1) + b1)
    out_e = jnp.einsum("ecf,efd->ecd", h, w2) + b2  # [E, cap, D]
    out_e = out_e * g[..., None]
    # scatter-add back to token positions (tokens picked by several experts
    # accumulate, unpicked tokens pass through as zeros — EC semantics)
    out = jnp.zeros((n_tok, d), x.dtype)
    out = out.at[idx.reshape(-1)].add(out_e.reshape(-1, d))
    return out.reshape(b, s, d)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """incubate.nn.functional.fused_dropout_add: dropout(x) + y in one
    fused op (XLA fuses the mask-mul-add chain natively)."""
    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """incubate.nn.functional.fused_matmul_bias: one GEMM + bias add
    (the reference's cublasLt epilogue fusion; XLA does it on the MXU).
    Rides the shared tensor matmul (centralized transpose handling)."""
    from ..tensor.linalg import matmul

    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        return out + bias
    return out


def swiglu(x, y=None, name=None):
    """incubate.nn.functional.swiglu: silu(x) * y; with y=None, x splits in
    half on the last axis (the Llama MLP gate form)."""
    xv = raw(x) if isinstance(x, Tensor) else jnp.asarray(x)
    if y is None:
        xv, yv = jnp.split(xv, 2, axis=-1)
    else:
        yv = raw(y) if isinstance(y, Tensor) else jnp.asarray(y)
    import jax as _jax

    return Tensor(_jax.nn.silu(xv) * yv)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", name=None):
    """incubate.nn.functional.fused_ec_moe — functional form of
    :class:`FusedEcMoe` (expert-choice routing over batched expert GEMMs)."""
    num_experts = raw(bmm0_weight).shape[0]
    out = _fused_ec_moe(
        raw(x) if isinstance(x, Tensor) else jnp.asarray(x),
        raw(gate) if isinstance(gate, Tensor) else jnp.asarray(gate),
        raw(bmm0_weight), raw(bmm0_bias), raw(bmm1_weight), raw(bmm1_bias),
        act_type, num_experts)
    return Tensor(out) if not isinstance(out, Tensor) else out


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0, name=None):
    """incubate.nn.functional.variable_length_memory_efficient_attention
    parity: [b, h, s, d] layout with per-sequence valid lengths. On TPU the
    static-shape form is a length-masked attention (the memory-efficiency
    the CUDA kernel buys is XLA's/flash's concern); routes through
    scaled_dot_product_attention, which shape-gates onto the Pallas flash
    kernel for long sequences."""
    if pre_cache_length:
        raise NotImplementedError(
            "variable_length_memory_efficient_attention: pre_cache_length "
            "is a CUDA prefix-cache extra with no path here; prepend the "
            "cache to key/value and extend kv_seq_lens instead")
    q = raw(query) if isinstance(query, Tensor) else jnp.asarray(query)
    k = raw(key) if isinstance(key, Tensor) else jnp.asarray(key)
    v = raw(value) if isinstance(value, Tensor) else jnp.asarray(value)
    sl = jnp.asarray(raw(seq_lens)).reshape(-1)
    kvl = jnp.asarray(raw(kv_seq_lens)).reshape(-1)
    b, h, sq, d = q.shape
    skv = k.shape[2]
    q_valid = jnp.arange(sq)[None, :] < sl[:, None]  # [b, sq]
    kv_valid = jnp.arange(skv)[None, :] < kvl[:, None]  # [b, skv]
    m = (q_valid[:, None, :, None] & kv_valid[:, None, None, :])
    if causal:
        # varlen sequences are START-aligned: query i of row b sits at
        # absolute position i + (kvl[b] - sl[b]), so it sees keys
        # j <= i + (kvl[b] - sl[b]) — not the padded-shape diagonal
        off = (kvl - sl)[:, None, None, None]
        i = jnp.arange(sq)[None, None, :, None]
        j = jnp.arange(skv)[None, None, None, :]
        m = m & (j <= i + off)
    if mask is not None:
        mv = raw(mask) if isinstance(mask, Tensor) else jnp.asarray(mask)
        if mv.dtype == jnp.bool_:
            attn_mask = Tensor(m & mv)
        else:
            # ADDITIVE mask (paddle semantics: 0 keep, -inf drop): add it
            # on top of the validity mask expressed additively
            neg = jnp.asarray(jnp.finfo(mv.dtype).min, mv.dtype)
            attn_mask = Tensor(jnp.where(m, mv, neg))
    else:
        attn_mask = Tensor(m)
    # to [b, s, h, d] (sdpa layout), masked, back
    out = F.scaled_dot_product_attention(
        Tensor(jnp.swapaxes(q, 1, 2)), Tensor(jnp.swapaxes(k, 1, 2)),
        Tensor(jnp.swapaxes(v, 1, 2)), attn_mask=attn_mask,
        is_causal=False, scale=scale)
    out = jnp.swapaxes(raw(out), 1, 2)
    # zero the padding queries (NaN-safe: fully-masked rows)
    out = jnp.where(q_valid[:, None, :, None], out, 0.0)
    return Tensor(jnp.nan_to_num(out))


def masked_multihead_attention(
        x, cache_kv=None, bias=None, src_mask=None, sequence_lengths=None,
        rotary_tensor=None, beam_cache_offset=None, qkv_out_scale=None,
        out_shift=None, num_heads=None, seq_len=1, rotary_emb_dims=0,
        use_neox_rotary_style=False, name=None, **kwargs):
    """incubate.nn.functional.masked_multihead_attention parity (the
    one-token decode-step attention behind LLM serving).

    Supported core: ``x`` [b, 3*h*d] packed qkv for ONE step, ``cache_kv``
    [2, b, h, max_len, d], ``sequence_lengths`` [b] giving the write
    position (REQUIRED: the first empty slot is not knowable statically,
    and silently writing slot 0 every call would make repeated decode
    steps attend to one token). Quantization/beam/rotary extras of the
    CUDA kernel raise if passed. Returns (out [b, h*d], updated cache_kv).
    """
    for extra, label in ((rotary_tensor, "rotary_tensor"),
                         (beam_cache_offset, "beam_cache_offset"),
                         (qkv_out_scale, "qkv_out_scale"),
                         (out_shift, "out_shift")):
        if extra is not None:
            raise NotImplementedError(
                f"masked_multihead_attention: {label} is a CUDA-kernel "
                "quantization/beam extra with no TPU path here")
    if cache_kv is None:
        raise ValueError("masked_multihead_attention requires cache_kv")
    import jax as _jax

    xv = raw(x) if isinstance(x, Tensor) else jnp.asarray(x)
    ck = raw(cache_kv) if isinstance(cache_kv, Tensor) else jnp.asarray(cache_kv)
    _, b, h, max_len, d = ck.shape
    qkv = xv.reshape(b, 3, h, d)
    if bias is not None:
        qkv = qkv + raw(bias).reshape(1, 3, h, d)
    q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [b, h, d]
    if sequence_lengths is None:
        # matching the other unsupported-extra guards: defaulting the write
        # position to 0 would silently overwrite slot 0 on every decode
        # step and attend to a single token
        raise NotImplementedError(
            "masked_multihead_attention: sequence_lengths is required on "
            "TPU — the CUDA kernel tracks the decode position internally; "
            "here the caller must pass the per-row write position [b]")
    t = jnp.asarray(raw(sequence_lengths)).reshape(-1)  # [b]
    # write the new k/v at position t per batch row
    onehot = _jax.nn.one_hot(t, max_len, dtype=ck.dtype)  # [b, max_len]
    k_cache = ck[0] * (1 - onehot[:, None, :, None]) + \
        k_new[:, :, None, :] * onehot[:, None, :, None]
    v_cache = ck[1] * (1 - onehot[:, None, :, None]) + \
        v_new[:, :, None, :] * onehot[:, None, :, None]
    valid = jnp.arange(max_len)[None, :] <= t[:, None]  # [b, max_len]
    logits = jnp.einsum("bhd,bhld->bhl", q, k_cache) / jnp.sqrt(
        jnp.asarray(d, jnp.float32)).astype(q.dtype)
    if src_mask is not None:
        logits = logits + raw(src_mask).reshape(b, 1, -1)[:, :, :max_len]
    logits = jnp.where(valid[:, None, :], logits,
                       jnp.asarray(-1e9, logits.dtype))
    probs = _jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhl,bhld->bhd", probs, v_cache).reshape(b, h * d)
    new_cache = jnp.stack([k_cache, v_cache], axis=0)
    return Tensor(out), Tensor(new_cache)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """incubate.nn.FusedBiasDropoutResidualLayerNorm layer over the
    existing functional (LN(residual + dropout(x + bias))). Parameters are
    flat with the reference's names (linear_bias / ln_scale / ln_bias), so
    reference checkpoints map key-for-key."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ..nn import initializer as I

        self.p = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True)

    def forward(self, x, residual):
        return fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            dropout_rate=self.p, ln_epsilon=self._epsilon,
            training=self.training)


def _make_functional_module():
    """paddle.incubate.nn.functional namespace parity. A REAL module
    registered in sys.modules so every reference import form works:
    ``from ...incubate.nn.functional import fused_linear`` and
    ``import ...incubate.nn.functional as F`` both resolve (a plain
    attribute object would fail those with ModuleNotFoundError)."""
    import sys
    import types

    this = sys.modules[__name__]
    mod = types.ModuleType(__name__ + ".functional")
    mod.__doc__ = "paddle.incubate.nn.functional parity (fused functionals)"

    class _Fwd(types.ModuleType):
        def __getattr__(self, name):
            try:
                return getattr(this, name)
            except AttributeError:
                raise AttributeError(
                    f"paddle.incubate.nn.functional has no attribute {name!r}"
                ) from None

    mod.__class__ = _Fwd
    sys.modules[mod.__name__] = mod
    return mod


functional = _make_functional_module()
