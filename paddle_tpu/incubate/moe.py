"""Mixture-of-Experts with expert parallelism.

Reference capability (SURVEY.md §2.3 "Expert parallel (EP/MoE)"):
`python/paddle/incubate/distributed/models/moe/moe_layer.py` — gshard/switch
gating with capacity, `global_scatter`/`global_gather` all-to-all dispatch
ops (CUDA), per-rank expert FFNs.

TPU-native design (GShard formulation): gating produces dispatch/combine
tensors; dispatch is an einsum into a dense [experts, capacity, hidden]
buffer, experts run as ONE batched matmul over the expert dim (MXU-friendly,
no ragged loops), combine is the transpose einsum. The expert dim is sharded
over a mesh axis, so GSPMD emits the token all-to-all that the reference's
global_scatter/global_gather implement by hand. Static capacity keeps shapes
XLA-compatible; dropped tokens (over capacity) pass through the residual,
exactly like capacity-factor semantics in the reference.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..framework.core import Tensor
from ..framework.op import defop, raw
from ..distributed import mesh as _mesh


def _expert_axis() -> Optional[str]:
    """Mesh axis carrying the expert dim: prefer a dedicated data axis."""
    m = _mesh.get_global_mesh()
    if m is None:
        return None
    for name in ("sharding", "dp", "sep"):
        if name in m.shape and m.shape[name] > 1:
            return name
    return None


def _aux_loss(probs, e, k):
    """gshard load-balancing loss: E^2/k * Σ_e density_e · mean-prob_e
    (shared by the capacity and dropless routing paths)."""
    density = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32), 0
    )
    return jnp.sum(density * jnp.mean(probs, 0)) * (e * e) / max(k, 1)


@defop(name="moe_gate_dispatch")
def _gshard_gating(logits, key, k, capacity, use_aux_noise):
    """Top-k gating with static capacity (gshard/switch).

    logits: [G, E] (G tokens). Returns (combine [G,E,C], dispatch bool
    [G,E,C], aux_loss scalar).
    """
    g, e = logits.shape
    if use_aux_noise and key is not None:
        logits = logits + jax.random.gumbel(key, logits.shape) * 0.01
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    combine = jnp.zeros((g, e), jnp.float32)
    remaining = probs
    position_in_expert = jnp.zeros((g, e), jnp.int32)
    fill = jnp.zeros((e,), jnp.int32)
    masks = []
    gates = []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)  # [G]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        gates.append((probs * onehot).sum(-1))
        # position of each token within its chosen expert queue
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + fill[None, :].astype(jnp.float32)
        pos = (pos * onehot).sum(-1).astype(jnp.int32)  # [G]
        keep = pos < capacity
        masks.append((onehot, pos, keep))
        fill = fill + onehot.sum(0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)

    aux = _aux_loss(probs, e, k)

    denom = sum(gt * m[2] for gt, m in zip(gates, masks))
    denom = jnp.maximum(denom, 1e-9)
    dispatch = jnp.zeros((g, e, capacity), bool)
    combine3 = jnp.zeros((g, e, capacity), jnp.float32)
    for gt, (onehot, pos, keep) in zip(gates, masks):
        w = (gt / denom) * keep.astype(jnp.float32)
        sel = onehot.astype(bool) & keep[:, None]
        oh_cap = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [G, C]
        combine3 = combine3 + w[:, None, None] * onehot[:, :, None] * oh_cap[:, None, :]
        dispatch = dispatch | (sel[:, :, None] & (oh_cap[:, None, :] > 0))
    return combine3, dispatch, aux


class MoELayer(nn.Layer):
    """GShard-style MoE FFN (paddle.incubate MoELayer parity).

    experts: number of expert FFNs (global). Weights are stored stacked
    [E, ...] with the expert dim sharded over the expert-parallel mesh axis.
    """

    def __init__(
        self,
        d_model: int,
        d_hidden: int,
        num_experts: int,
        top_k: int = 2,
        capacity_factor: float = 1.25,
        gate: str = "gshard",
        aux_loss_weight: float = 1e-2,
        activation=None,
        drop_tokens: bool = True,
    ):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.act = activation or F.gelu
        # drop_tokens=False → DROPLESS routing over the Pallas grouped-matmul
        # kernel (megablox-style): no capacity, no dropped tokens; experts
        # see exactly their routed tokens (ragged groups). Currently runs
        # with replicated expert weights (the capacity path carries the
        # EP-sharded all-to-all).
        self.drop_tokens = drop_tokens
        self.gate = nn.Linear(d_model, num_experts)
        init = I.XavierNormal()
        self.w_in = self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=init
        )
        self.b_in = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w_out = self.create_parameter(
            [num_experts, d_hidden, d_model], default_initializer=init
        )
        self.b_out = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        ax = _expert_axis()
        if (drop_tokens and ax is not None
                and num_experts % _mesh.mesh_axis_size(ax) == 0):
            # EP sharding only for the capacity path; the dropless grouped-
            # matmul kernel runs with replicated expert weights
            for p in (self.w_in, self.b_in, self.w_out, self.b_out):
                p.dist_spec = P(ax)
                p.is_distributed = True
        self.last_aux_loss = None

    def forward(self, x):
        b, t, h = x.shape
        g = b * t
        flat = x.reshape([g, h])
        logits = self.gate(flat)
        if not self.drop_tokens:
            out, aux = _moe_apply_dropless(
                flat, logits, self.w_in, self.b_in, self.w_out, self.b_out,
                self.act, self.top_k,
            )
            self.last_aux_loss = aux * self.aux_loss_weight
            return out.reshape([b, t, h])
        capacity = max(
            self.top_k, int(math.ceil(self.top_k * self.capacity_factor * g / self.num_experts))
        )
        from ..framework import rng as _rng

        key = _rng.next_key() if self.training else None
        combine, dispatch, aux = _gshard_gating(
            logits, key, self.top_k, capacity, self.training
        )
        self.last_aux_loss = aux * self.aux_loss_weight
        out = _moe_apply(
            flat, combine, dispatch, self.w_in, self.b_in, self.w_out, self.b_out,
            self.act,
        )
        return out.reshape([b, t, h])


@defop(name="moe_apply")
def _moe_apply(flat, combine, dispatch, w_in, b_in, w_out, b_out, act):
    # dispatch tokens into per-expert buffers: [E, C, h]
    expert_in = jnp.einsum("gec,gh->ech", dispatch.astype(flat.dtype), flat)
    spec = None
    m = _mesh.get_global_mesh()
    ax = _expert_axis()
    if m is not None and ax is not None and expert_in.shape[0] % m.shape[ax] == 0:
        # pin the expert buffers to the expert axis — this is the all-to-all
        expert_in = _mesh.sharding_constraint(expert_in, P(ax))
    hidden = raw(act(jnp.einsum("ech,ehf->ecf", expert_in, w_in) + b_in))
    expert_out = jnp.einsum("ecf,efh->ech", hidden, w_out) + b_out
    if m is not None and ax is not None and expert_out.shape[0] % m.shape[ax] == 0:
        expert_out = _mesh.sharding_constraint(expert_out, P(ax))
    # combine back to tokens
    return jnp.einsum("gec,ech->gh", combine.astype(flat.dtype), expert_out)


@defop(name="moe_apply_dropless")
def _moe_apply_dropless(flat, logits, w_in, b_in, w_out, b_out, act, top_k):
    """Dropless MoE FFN over the Pallas grouped-matmul kernel.

    Token copies are sorted by routed expert; the two expert GEMMs run as
    ragged grouped matmuls with data-dependent group sizes (no capacity, no
    dropped tokens — the reference needs `global_scatter` + per-expert GEMM
    loops for this; megablox-style kernels are the TPU-native equivalent).
    Returns (out [G, H], aux_loss).
    """
    from ..ops.pallas.grouped_matmul import grouped_matmul

    g, h = flat.shape
    e = w_in.shape[0]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, top_k)  # [G, k]
    gates = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)

    aux = _aux_loss(probs, e, top_k)

    gk = g * top_k
    expert_ids = topi.reshape(-1)  # [gk]
    order = jnp.argsort(expert_ids)  # stable: ties keep token order
    sizes = jnp.bincount(expert_ids, length=e)  # dynamic group sizes
    row_gid = expert_ids[order]
    xs = flat[order // top_k].astype(flat.dtype)  # [gk, H] sorted copies

    # measured on v5e (8k tokens, 1024->4096, 8 experts): 512-row blocks
    # are ~6% faster than 128 (less per-visit overhead). Use them only
    # once the padding tail is amortized (gk >= 2048 keeps the tail under
    # 25%; at gk just above 512 it would nearly double the row tiles);
    # tiny inputs keep a pow2 block so the tail stays bounded
    if gk >= 2048:
        block_m = 512
    elif gk >= 128:
        block_m = 128
    else:
        block_m = max(8, 1 << (gk - 1).bit_length())
    pad = (-gk) % block_m
    xs_p = jnp.pad(xs, ((0, pad), (0, 0)))

    h1 = grouped_matmul(xs_p, w_in, sizes, block_m=block_m)[:gk]
    h1 = h1 + b_in[row_gid, 0]
    a = raw(act(h1)).astype(flat.dtype)
    a_p = jnp.pad(a, ((0, pad), (0, 0)))
    y = grouped_matmul(a_p, w_out, sizes, block_m=block_m)[:gk]
    y = y + b_out[row_gid, 0]

    inv = jnp.argsort(order)  # unsort copies back to (token, slot) order
    y_tok = y[inv].reshape(g, top_k, h)
    out = jnp.sum(gates[..., None].astype(flat.dtype) * y_tok, axis=1)
    return out, aux


# ------------------------------------------------- global_scatter / gather --
def global_scatter(x, local_count=None, global_count=None, group=None):
    """Reference `global_scatter` op parity: the token all-to-all. Under SPMD
    this is a resharding of the expert-major buffer onto the expert axis."""
    ax = _expert_axis()
    if ax is None:
        return x
    return Tensor(_mesh.sharding_constraint(raw(x), P(ax)))


def global_gather(x, local_count=None, global_count=None, group=None):
    ax = _expert_axis()
    if ax is None:
        return x
    return Tensor(_mesh.sharding_constraint(raw(x), P()))
