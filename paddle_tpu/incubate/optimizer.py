"""paddle.incubate.optimizer — LookAhead and ModelAverage.

Reference capability: ``python/paddle/incubate/optimizer/lookahead.py`` and
``modelaverage.py``. Both are host-side wrappers around the pytree update
rules — the inner optimizer's compiled step stays a single XLA program; the
slow-weights / averaging math is pure jnp on the parameter leaves.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..autograd import no_grad
from ..framework.op import raw

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """Lookahead optimizer (Zhang et al. 2019): wrap any inner optimizer;
    every ``k`` fast steps, slow weights move ``alpha`` toward the fast
    weights and the fast weights reset onto them.

    Mirrors the reference wrapper API: ``step`` / ``minimize`` /
    ``clear_grad`` / ``state_dict`` / ``set_state_dict``.
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner_optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be within [0, 1], got {alpha}")
        if not (isinstance(k, int) and k > 0):
            raise ValueError(f"k must be a positive integer, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._global_step = 0
        # slow weights start at phi_0 = the parameters at wrap time (the
        # paper's initialization; zeros would drag the first sync toward 0)
        self._slow = {
            i: jnp.asarray(raw(p), jnp.float32)
            for i, p in enumerate(self._parameter_list)
            if p.trainable
        }

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._global_step += 1
        if self._global_step % self.k:
            return
        masters = getattr(self.inner_optimizer, "_master", {})
        for i, p in enumerate(self._parameter_list):
            if not p.trainable:
                continue
            pv = raw(p)
            # under O2 the fp32 master is the source of truth — reading the
            # bf16 parameter would round sub-bf16 progress out of the
            # master at every sync
            fast = masters.get(i, pv).astype(jnp.float32)
            slow = self._slow.get(i)
            if slow is None:  # param became trainable after wrap
                slow = fast
            slow = slow + self.alpha * (fast - slow)
            self._slow[i] = slow
            p._rebind(slow.astype(pv.dtype))
            # the master copy must follow the rebind or the next inner step
            # would resurrect the pre-sync fast weights
            if i in masters:
                masters[i] = slow

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        state = self.inner_optimizer.state_dict()
        state["@lookahead_step"] = self._global_step
        for i, s in self._slow.items():
            state[f"@lookahead_slow_{i}"] = s
        return state

    def set_state_dict(self, state):
        state = dict(state)
        self._global_step = int(state.pop("@lookahead_step", 0))
        self._slow = {
            int(k.rsplit("_", 1)[1]): jnp.asarray(state.pop(k))
            for k in [k for k in state if k.startswith("@lookahead_slow_")]
        }
        self.inner_optimizer.set_state_dict(state)


class ModelAverage:
    """Polyak-style parameter averaging over a sliding window.

    Accumulate with ``step()`` after every optimizer step; evaluate under
    ``with model_average.apply():`` (parameters temporarily rebind to the
    average) and train on via ``restore()`` semantics — same triple-sum
    window rotation as the reference (sum_1/sum_2/sum_3 with
    num_accumulates rolling into old_num_accumulates at the window bound).
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("parameters must be provided")
        self._parameter_list = list(parameters)
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        n = len(self._parameter_list)
        # two-window accumulation: sum_1 is the open window, sum_3 the last
        # closed one (the reference's sum_2 staging buffer collapses into
        # this — the average it yields is identical)
        self._sum_1 = [None] * n
        self._sum_3 = [None] * n
        self._num_accumulates = 0
        self._old_num_accumulates = 0
        self._num_updates = 0
        self._restore = None

    @no_grad()
    def step(self):
        self._num_updates += 1
        window = max(
            self.min_average_window,
            min(self.max_average_window,
                int(self._num_updates * self.average_window)),
        )
        if self._num_accumulates >= window:
            # rotate: sum_3 absorbs the closed window, sum_1 restarts
            for i in range(len(self._parameter_list)):
                self._sum_3[i] = self._sum_1[i]
                self._sum_1[i] = None
            self._old_num_accumulates = self._num_accumulates
            self._num_accumulates = 0
        for i, p in enumerate(self._parameter_list):
            v = raw(p).astype(jnp.float32)
            self._sum_1[i] = v if self._sum_1[i] is None else self._sum_1[i] + v
        self._num_accumulates += 1

    def _average(self, i):
        total = None
        for s in (self._sum_1[i], self._sum_3[i]):
            if s is not None:
                total = s if total is None else total + s
        count = self._num_accumulates + self._old_num_accumulates
        if total is None or count == 0:
            return None
        return total / count

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Rebind every tracked parameter to its running average.

        ``need_restore=False`` leaves the parameters bound to the average
        on exit; the saved fast weights are KEPT so a later manual
        ``restore()`` still works (reference semantics).
        """
        if self._restore is not None:
            raise RuntimeError("ModelAverage.apply() calls cannot nest")
        saved = []
        for i, p in enumerate(self._parameter_list):
            avg = self._average(i)
            saved.append(raw(p))
            if avg is not None:
                p._rebind(avg.astype(saved[-1].dtype))
        self._restore = saved
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._restore is None:
            return
        for p, v in zip(self._parameter_list, self._restore):
            p._rebind(v)
        self._restore = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # parity shim: the reference's static-mode ModelAverage.minimize is
        # a no-op on the loss; accumulation happens via step()
        self.step()
