"""paddle.regularizer parity: weight-decay regularizers (importable module
so both `paddle.regularizer.L2Decay` and
`from paddle_tpu.regularizer import L2Decay` work)."""
from .optimizer import L1Decay, L2Decay  # noqa: F401
