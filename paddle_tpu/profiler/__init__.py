"""paddle.profiler parity — tracing & performance summaries.

Reference capability (SURVEY.md §5 "Tracing/profiling"):
`paddle.profiler.Profiler` with host tracer (scoped `RecordEvent`) + CUPTI
device tracer, Chrome-trace export, scheduler (`make_scheduler`), and
`summary()` tables.

TPU-native design: the device tracer is the XLA/PJRT profiler
(`jax.profiler.start_trace` → XPlane, viewable in TensorBoard/Perfetto/xprof);
host annotations are `jax.profiler.TraceAnnotation`s, which the runtime
stitches into the same timeline. The host-side op timer used for `summary()`
is a lightweight wall-clock aggregator (the per-op C++ timer of the
reference is meaningless under whole-program XLA execution — the compiled
step is the unit)."""
from __future__ import annotations

import collections
import contextlib
import os
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

from .. import runtime as _runtime


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int = 0, ready: int = 0, record: int = 1, repeat: int = 0, skip_first: int = 0):
    """paddle.profiler.make_scheduler parity: step-state machine."""
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory: keep the XPlane/trace files under dir_name."""

    def handler(prof):
        prof._export_dir = dir_name

    return handler


class RecordEvent:
    """Scoped host annotation (reference: platform::RecordEvent).

    Shows up in the XLA trace timeline and in Profiler.summary().
    """

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        # FFI timestamp only when the native tracer is actually recording —
        # two ctypes calls + a mutex per event is real overhead in an
        # untraced training loop.
        self._t0_ns = _runtime.now_ns() if _runtime.trace_enabled() else None
        _host_events[self.name][0] += 1

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            _host_events[self.name][1] += time.perf_counter() - self._t0
            if self._t0_ns is not None and _runtime.trace_enabled():
                import threading as _threading

                _runtime.trace_record(
                    self.name,
                    self._t0_ns,
                    _runtime.now_ns() - self._t0_ns,
                    tid=_threading.get_ident() % (1 << 31),
                )
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


_host_events = collections.defaultdict(lambda: [0, 0.0])  # name -> [count, secs]


class Profiler:
    def __init__(
        self,
        *,
        targets: Optional[Iterable] = None,
        scheduler=None,
        on_trace_ready: Optional[Callable] = None,
        timer_only: bool = False,
        record_shapes: bool = False,
        profile_memory: bool = False,
        with_flops: bool = False,
    ):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        else:
            self._scheduler = None
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._export_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._step = 0
        self._tracing = False
        self._step_times = []
        self._last_step_t = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._last_step_t = time.perf_counter()
        if not self._timer_only and self._scheduler is None:
            self._start_trace()
        return self

    def stop(self):
        if self._tracing:
            self._stop_trace()
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        return self

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        if self._scheduler is not None and not self._timer_only:
            state = self._scheduler(self._step)
            if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
                if not self._tracing:
                    self._start_trace()
            elif self._tracing:
                self._stop_trace()

    def _start_trace(self):
        _runtime.trace_start()
        try:
            jax.profiler.start_trace(self._export_dir)
            self._tracing = True
        except Exception:
            # keep the native host tracer symmetric with the failed device
            # trace — otherwise it stays on (and accumulating) for the rest
            # of the process.
            _runtime.trace_stop()
            self._tracing = False

    def _stop_trace(self):
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self._tracing = False
        _runtime.trace_stop()
        # Export host RecordEvents as a chrome trace alongside the XPlane
        # files (reference: chrometracing_logger.cc output).
        events = _runtime.trace_export()
        if events:
            import json

            os.makedirs(self._export_dir, exist_ok=True)
            with open(os.path.join(self._export_dir, "host_trace.json"), "w") as f:
                json.dump({"traceEvents": events}, f)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting ----------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        lines = ["-- paddle_tpu profiler summary " + "-" * 30]
        if self._step_times:
            ts = self._step_times
            lines.append(
                f"steps: {len(ts)}  avg: {sum(ts) / len(ts) * 1e3:.2f} ms  "
                f"min: {min(ts) * 1e3:.2f} ms  max: {max(ts) * 1e3:.2f} ms"
            )
        if _host_events:
            lines.append(f"{'event':40s} {'count':>8s} {'total ms':>12s}")
            for name, (cnt, secs) in sorted(_host_events.items(), key=lambda kv: -kv[1][1]):
                lines.append(f"{name:40s} {cnt:8d} {secs * 1e3:12.2f}")
        if self._tracing or os.path.isdir(self._export_dir):
            lines.append(f"device trace (XPlane): {self._export_dir}")
        out = "\n".join(lines)
        print(out)
        return out


@contextlib.contextmanager
def profile(dir_name: str = "/tmp/paddle_tpu_profile"):
    """Simple context: trace everything inside to `dir_name`."""
    jax.profiler.start_trace(dir_name)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_profiler(dir_name: str = "/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(dir_name)


def stop_profiler(*a, **k):
    jax.profiler.stop_trace()


load_profiler_result = None  # chrome-trace reload: covered by TensorBoard/xprof
