"""paddle.profiler parity — tracing & performance summaries.

Reference capability (SURVEY.md §5 "Tracing/profiling"):
`paddle.profiler.Profiler` with host tracer (scoped `RecordEvent`) + CUPTI
device tracer, Chrome-trace export, scheduler (`make_scheduler`), and
`summary()` tables.

TPU-native design: the device tracer is the XLA/PJRT profiler
(`jax.profiler.start_trace` → XPlane, viewable in TensorBoard/Perfetto/xprof);
host annotations are `jax.profiler.TraceAnnotation`s, which the runtime
stitches into the same timeline. The host-side op timer used for `summary()`
is a lightweight wall-clock aggregator (the per-op C++ timer of the
reference is meaningless under whole-program XLA execution — the compiled
step is the unit)."""
from __future__ import annotations

import collections
import contextlib
import json
import os
import time
from enum import Enum
from typing import Callable, Iterable, List, Optional, Union

import jax

from .. import runtime as _runtime


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int = 0, ready: int = 0, record: int = 1, repeat: int = 0, skip_first: int = 0):
    """paddle.profiler.make_scheduler parity: step-state machine."""
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


class SortedKeys(Enum):
    """paddle.profiler.SortedKeys parity (host-timer subset)."""

    CPUTotal = 0
    CPUAvg = 1
    Calls = 2
    Name = 3


#: string aliases accepted anywhere a SortedKeys is (paddle passes enums;
#: ad-hoc scripts pass strings)
_SORT_ALIASES = {
    "total": SortedKeys.CPUTotal,
    "avg": SortedKeys.CPUAvg,
    "count": SortedKeys.Calls,
    "calls": SortedKeys.Calls,
    "name": SortedKeys.Name,
}


def _resolve_sort(sorted_by) -> SortedKeys:
    if sorted_by is None:
        return SortedKeys.CPUTotal
    if isinstance(sorted_by, SortedKeys):
        return sorted_by
    key = _SORT_ALIASES.get(str(sorted_by).lower())
    if key is None:
        raise ValueError(
            f"summary(sorted_by={sorted_by!r}): expected a SortedKeys or one "
            f"of {sorted(_SORT_ALIASES)}")
    return key


_TIME_UNITS = {"s": 1.0, "ms": 1e3, "us": 1e6}


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory: keep the XPlane/trace files under dir_name;
    `worker_name` prefixes the host-trace file (``{worker}_host_trace.json``)
    so multi-worker runs exporting into a shared dir don't clobber each
    other. The config is also applied at Profiler construction (via the
    attribute below) — the host trace is written during ``_stop_trace``,
    BEFORE the on_trace_ready callback fires."""

    def handler(prof):
        prof._export_dir = dir_name
        if worker_name:
            prof._worker_name = worker_name

    handler._export_config = (dir_name, worker_name)
    return handler


class RecordEvent:
    """Scoped host annotation (reference: platform::RecordEvent).

    Shows up in the XLA trace timeline and in Profiler.summary().
    """

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        # FFI timestamp only when the native tracer is actually recording —
        # two ctypes calls + a mutex per event is real overhead in an
        # untraced training loop.
        self._t0_ns = _runtime.now_ns() if _runtime.trace_enabled() else None
        _host_events[self.name][0] += 1

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            _host_events[self.name][1] += time.perf_counter() - self._t0
            if self._t0_ns is not None and _runtime.trace_enabled():
                import threading as _threading

                _runtime.trace_record(
                    self.name,
                    self._t0_ns,
                    _runtime.now_ns() - self._t0_ns,
                    tid=_threading.get_ident() % (1 << 31),
                )
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


_host_events = collections.defaultdict(lambda: [0, 0.0])  # name -> [count, secs]


def reset_host_events() -> None:
    """Clear the process-global RecordEvent aggregator. The aggregator is
    deliberately process-wide (mirrors the reference's global host tracer),
    so back-to-back Profiler runs — and test cases — must reset it between
    runs or the second summary() reports the first run's counts too."""
    _host_events.clear()


class Profiler:
    def __init__(
        self,
        *,
        targets: Optional[Iterable] = None,
        scheduler=None,
        on_trace_ready: Optional[Callable] = None,
        timer_only: bool = False,
        record_shapes: bool = False,
        profile_memory: bool = False,
        with_flops: bool = False,
    ):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        else:
            self._scheduler = None
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._export_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._worker_name = None
        # export_chrome_tracing carries its config on the handler: apply it
        # NOW, not at stop() — the host trace file is written in
        # _stop_trace, before the on_trace_ready callback runs
        cfg = getattr(on_trace_ready, "_export_config", None)
        if cfg is not None:
            self._export_dir = cfg[0]
            self._worker_name = cfg[1]
        self._step = 0
        self._tracing = False
        self._step_times = []
        self._last_step_t = None
        #: every scheduler state as applied, in order — step 0's state first
        #: (tests pin the sequence against make_scheduler's)
        self._state_history: List[ProfilerState] = []

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._last_step_t = time.perf_counter()
        if self._timer_only:
            return self
        if self._scheduler is None:
            self._start_trace()
        else:
            # the scheduler's step-0 state applies to the FIRST step, which
            # runs between start() and the first step() call — consulting
            # only inside step() (post-increment) skipped it entirely and
            # shifted skip_first by one
            self._apply_state(self._scheduler(self._step))
        return self

    def stop(self):
        if self._tracing:
            self._stop_trace()
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        return self

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        if self._scheduler is not None and not self._timer_only:
            self._apply_state(self._scheduler(self._step))

    def _apply_state(self, state: ProfilerState):
        self._state_history.append(state)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            if not self._tracing:
                self._start_trace()
        elif self._tracing:
            self._stop_trace()

    def _start_trace(self):
        _runtime.trace_start()
        try:
            jax.profiler.start_trace(self._export_dir)
            self._tracing = True
        except Exception:
            # keep the native host tracer symmetric with the failed device
            # trace — otherwise it stays on (and accumulating) for the rest
            # of the process.
            _runtime.trace_stop()
            self._tracing = False

    def _stop_trace(self):
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        self._tracing = False
        _runtime.trace_stop()
        # Export host RecordEvents as a chrome trace alongside the XPlane
        # files (reference: chrometracing_logger.cc output).
        events = _runtime.trace_export()
        if events:
            fname = (f"{self._worker_name}_host_trace.json"
                     if self._worker_name else "host_trace.json")
            os.makedirs(self._export_dir, exist_ok=True)
            with open(os.path.join(self._export_dir, fname), "w") as f:
                json.dump({"traceEvents": events}, f)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- reporting ----------------------------------------------------------
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        """Host-timer report. `sorted_by` orders the event table
        (SortedKeys or "total"/"avg"/"count"/"name"; default total time,
        descending); `time_unit` is one of "s"/"ms"/"us"."""
        unit = str(time_unit).lower()
        if unit not in _TIME_UNITS:
            raise ValueError(
                f"summary(time_unit={time_unit!r}): expected one of "
                f"{sorted(_TIME_UNITS)}")
        scale = _TIME_UNITS[unit]
        key = _resolve_sort(sorted_by)
        lines = ["-- paddle_tpu profiler summary " + "-" * 30]
        if self._step_times:
            ts = self._step_times
            lines.append(
                f"steps: {len(ts)}  avg: {sum(ts) / len(ts) * scale:.2f} {unit}  "
                f"min: {min(ts) * scale:.2f} {unit}  max: {max(ts) * scale:.2f} {unit}"
            )
        if _host_events:
            items = list(_host_events.items())
            if key is SortedKeys.Name:
                items.sort(key=lambda kv: kv[0])
            elif key is SortedKeys.Calls:
                items.sort(key=lambda kv: (-kv[1][0], kv[0]))
            elif key is SortedKeys.CPUAvg:
                items.sort(key=lambda kv: (-kv[1][1] / max(kv[1][0], 1), kv[0]))
            else:
                items.sort(key=lambda kv: (-kv[1][1], kv[0]))
            lines.append(f"{'event':40s} {'count':>8s} "
                         f"{'total ' + unit:>12s} {'avg ' + unit:>12s}")
            for name, (cnt, secs) in items:
                lines.append(
                    f"{name:40s} {cnt:8d} {secs * scale:12.2f} "
                    f"{secs * scale / max(cnt, 1):12.2f}")
        if self._tracing or os.path.isdir(self._export_dir):
            lines.append(f"device trace (XPlane): {self._export_dir}")
        out = "\n".join(lines)
        print(out)
        return out


@contextlib.contextmanager
def profile(dir_name: str = "/tmp/paddle_tpu_profile"):
    """Simple context: trace everything inside to `dir_name`."""
    jax.profiler.start_trace(dir_name)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_profiler(dir_name: str = "/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(dir_name)


def stop_profiler(*a, **k):
    jax.profiler.stop_trace()


class ProfilerResult:
    """Programmatic view of an exported host chrome trace
    (``host_trace.json`` / ``{worker}_host_trace.json``).

    The device-side XPlane files stay in TensorBoard/xprof territory; this
    covers the host RecordEvent timeline — enough for tests and scripted
    assertions ("did my_region run 5 times and stay under 2ms?")."""

    def __init__(self, path: str, events: List[dict]):
        self.path = path
        #: raw chrome-trace event dicts (name/ph/ts/dur in microseconds)
        self.events = events

    def __len__(self) -> int:
        return len(self.events)

    def names(self) -> List[str]:
        return sorted({e.get("name") for e in self.events if e.get("name")})

    def _named(self, name: str) -> List[dict]:
        return [e for e in self.events if e.get("name") == name]

    def count(self, name: str) -> int:
        return len(self._named(name))

    def total_duration(self, name: str) -> float:
        """Summed duration of complete ("ph": "X") events, microseconds."""
        return float(sum(e.get("dur", 0) for e in self._named(name)
                         if e.get("ph", "X") == "X"))

    def time_range(self) -> Optional[tuple]:
        """(first_ts, last_end_ts) over all events, microseconds."""
        spans = [(e["ts"], e["ts"] + e.get("dur", 0))
                 for e in self.events if "ts" in e]
        if not spans:
            return None
        return min(s for s, _ in spans), max(e for _, e in spans)


def load_profiler_result(file_path: str) -> ProfilerResult:
    """Reload an exported host trace for programmatic assertions
    (paddle.profiler.load_profiler_result parity, host-trace scope).

    Accepts the JSON file itself or the export directory — in a directory,
    ``host_trace.json`` is preferred, else the lexicographically first
    ``*_host_trace.json`` (worker-named exports)."""
    path = file_path
    if os.path.isdir(path):
        default = os.path.join(path, "host_trace.json")
        if os.path.isfile(default):
            path = default
        else:
            named = sorted(n for n in os.listdir(path)
                           if n.endswith("_host_trace.json"))
            if not named:
                raise FileNotFoundError(
                    f"load_profiler_result({file_path!r}): no "
                    "host_trace.json or *_host_trace.json in directory")
            path = os.path.join(file_path, named[0])
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    return ProfilerResult(path, list(events))
