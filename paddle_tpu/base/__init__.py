"""paddle.base path compatibility (the reference renamed ``paddle.fluid``
to ``paddle.base`` in 2.6; many downstream scripts still import framework
internals through it).

This maps the commonly-imported names onto their owners here:
``paddle.base.core`` -> :mod:`paddle_tpu.framework.core` (Tensor/tape)
augmented with the capability predicates scripts poke at, and
``paddle.base.framework`` -> :mod:`paddle_tpu.framework`.
"""
from .. import framework  # noqa: F401
from ..framework import core  # noqa: F401
from ..device import (  # noqa: F401
    is_compiled_with_cuda,
    is_compiled_with_rocm,
    is_compiled_with_xpu,
)

# scripts frequently call these through base.core
core.is_compiled_with_cuda = is_compiled_with_cuda
core.is_compiled_with_rocm = is_compiled_with_rocm
core.is_compiled_with_xpu = is_compiled_with_xpu
