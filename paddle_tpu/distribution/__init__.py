"""paddle.distribution parity — probability distributions, transforms, KL.

Reference: ``python/paddle/distribution/`` (Distribution base with
sample/rsample/log_prob/entropy, the named distribution family, the Transform
family, and a (p,q)-type-registered ``kl_divergence``). TPU-native design:
every density / sampler is a pure jnp expression over ``jax.random`` keys
(drawn from the framework RNG so sampling is reproducible under seed() and
traceable under jit), so distributions compose with jit/vmap/grad — reparam
(rsample) gradients come for free from the functional form.
"""
from __future__ import annotations

import math
from typing import Dict, Tuple, Type

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..framework import rng as _rng
from ..framework.core import Tensor


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) else x


def _wrap(v):
    return Tensor(v)


def _shape(sample_shape):
    if sample_shape is None:
        return ()
    if isinstance(sample_shape, int):
        return (sample_shape,)
    return tuple(int(s) for s in sample_shape)


class Distribution:
    """Base class (reference: distribution/distribution.py)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        # default: sampling without grad = rsample with stopped gradient
        return _wrap(jax.lax.stop_gradient(_val(self.rsample(shape))))

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _wrap(jnp.exp(_val(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _key(self):
        return _rng.next_key()


# ---------------------------------------------------------------------------
# Continuous
# ---------------------------------------------------------------------------
class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale**2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(self.scale, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        dtype = jnp.result_type(self.loc, self.scale)
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        eps = jax.random.normal(self._key(), shape, dtype)
        return _wrap(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _val(value)
        var = self.scale**2
        return _wrap(
            -((v - self.loc) ** 2) / (2 * var)
            - jnp.log(self.scale)
            - 0.5 * math.log(2 * math.pi)
        )

    def entropy(self):
        out = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return _wrap(jnp.broadcast_to(out, self.batch_shape))

    def cdf(self, value):
        v = _val(value)
        return _wrap(0.5 * (1 + jsp.erf((v - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, q):
        qv = _val(q)
        return _wrap(self.loc + self.scale * math.sqrt(2) * jsp.erfinv(2 * qv - 1))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return _wrap(jnp.exp(self.loc + self.scale**2 / 2))

    @property
    def variance(self):
        s2 = self.scale**2
        return _wrap((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def rsample(self, shape=()):
        return _wrap(jnp.exp(_val(self._base.rsample(shape))))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(_val(self._base.log_prob(jnp.log(v))) - jnp.log(v))

    def entropy(self):
        return _wrap(_val(self._base.entropy()) + self.loc)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return _wrap((self.low + self.high) / 2)

    @property
    def variance(self):
        return _wrap((self.high - self.low) ** 2 / 12)

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape)
        return _wrap(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _wrap(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(2 * self.scale**2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(math.sqrt(2) * self.scale, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=-0.5, maxval=0.5)
        return _wrap(self.loc - self.scale * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(-jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale))

    def entropy(self):
        return _wrap(jnp.broadcast_to(1 + jnp.log(2 * self.scale), self.batch_shape))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc + self.scale * jnp.euler_gamma, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(math.pi**2 / 6 * self.scale**2, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        g = jax.random.gumbel(self._key(), shape)
        return _wrap(self.loc + self.scale * g)

    def log_prob(self, value):
        z = (_val(value) - self.loc) / self.scale
        return _wrap(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.scale) + 1 + jnp.euler_gamma, self.batch_shape))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=1e-7, maxval=1 - 1e-7)
        return _wrap(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    def log_prob(self, value):
        z = (_val(value) - self.loc) / self.scale
        return _wrap(-math.log(math.pi) - jnp.log(self.scale) - jnp.log1p(z**2))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(4 * math.pi * self.scale), self.batch_shape))

    def cdf(self, value):
        z = (_val(value) - self.loc) / self.scale
        return _wrap(jnp.arctan(z) / math.pi + 0.5)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _wrap(1 / self.rate)

    @property
    def variance(self):
        return _wrap(1 / self.rate**2)

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        e = jax.random.exponential(self._key(), shape)
        return _wrap(e / self.rate)

    def log_prob(self, value):
        v = _val(value)
        return _wrap(jnp.where(v >= 0, jnp.log(self.rate) - self.rate * v, -jnp.inf))

    def entropy(self):
        return _wrap(1 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _val(concentration)
        self.rate = _val(rate)
        super().__init__(
            jnp.broadcast_shapes(self.concentration.shape, self.rate.shape)
        )

    @property
    def mean(self):
        return _wrap(self.concentration / self.rate)

    @property
    def variance(self):
        return _wrap(self.concentration / self.rate**2)

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        g = jax.random.gamma(self._key(), jnp.broadcast_to(self.concentration, shape))
        return _wrap(g / self.rate)

    def log_prob(self, value):
        v = _val(value)
        a, b = self.concentration, self.rate
        return _wrap(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - jsp.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return _wrap(a - jnp.log(b) + jsp.gammaln(a) + (1 - a) * jsp.digamma(a))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return _wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(self.alpha * self.beta / (s**2 * (s + 1)))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        k1, k2 = jax.random.split(self._key())
        ga = jax.random.gamma(k1, jnp.broadcast_to(self.alpha, shape))
        gb = jax.random.gamma(k2, jnp.broadcast_to(self.beta, shape))
        return _wrap(ga / (ga + gb))

    def log_prob(self, value):
        v = _val(value)
        a, b = self.alpha, self.beta
        return _wrap((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - jsp.betaln(a, b))

    def entropy(self):
        a, b = self.alpha, self.beta
        return _wrap(
            jsp.betaln(a, b)
            - (a - 1) * jsp.digamma(a)
            - (b - 1) * jsp.digamma(b)
            + (a + b - 2) * jsp.digamma(a + b)
        )


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.concentration / self.concentration.sum(-1, keepdims=True))

    @property
    def variance(self):
        a = self.concentration
        a0 = a.sum(-1, keepdims=True)
        return _wrap(a * (a0 - a) / (a0**2 * (a0 + 1)))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape + self.event_shape
        g = jax.random.gamma(self._key(), jnp.broadcast_to(self.concentration, shape))
        return _wrap(g / g.sum(-1, keepdims=True))

    def log_prob(self, value):
        v = _val(value)
        a = self.concentration
        norm = jsp.gammaln(a.sum(-1)) - jsp.gammaln(a).sum(-1)
        return _wrap(((a - 1) * jnp.log(v)).sum(-1) + norm)

    def entropy(self):
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        lnB = jsp.gammaln(a).sum(-1) - jsp.gammaln(a0)
        return _wrap(
            lnB
            + (a0 - k) * jsp.digamma(a0)
            - ((a - 1) * jsp.digamma(a)).sum(-1)
        )


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _val(df)
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(
            jnp.broadcast_shapes(self.df.shape, self.loc.shape, self.scale.shape)
        )

    @property
    def mean(self):
        return _wrap(jnp.where(self.df > 1, jnp.broadcast_to(self.loc, self.batch_shape), jnp.nan))

    @property
    def variance(self):
        v = jnp.where(
            self.df > 2,
            self.scale**2 * self.df / (self.df - 2),
            jnp.where(self.df > 1, jnp.inf, jnp.nan),
        )
        return _wrap(jnp.broadcast_to(v, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        t = jax.random.t(self._key(), jnp.broadcast_to(self.df, shape), shape)
        return _wrap(self.loc + self.scale * t)

    def log_prob(self, value):
        z = (_val(value) - self.loc) / self.scale
        df = self.df
        return _wrap(
            jsp.gammaln((df + 1) / 2)
            - jsp.gammaln(df / 2)
            - 0.5 * jnp.log(df * math.pi)
            - jnp.log(self.scale)
            - (df + 1) / 2 * jnp.log1p(z**2 / df)
        )


# ---------------------------------------------------------------------------
# Discrete
# ---------------------------------------------------------------------------
class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _val(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _val(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return _wrap(self.probs)

    @property
    def variance(self):
        return _wrap(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(
            jax.random.bernoulli(self._key(), self.probs, shape).astype(jnp.float32)
        )

    def rsample(self, shape=(), temperature=1.0):
        # relaxed Bernoulli (Gumbel-sigmoid), matching paddle's rsample
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=1e-6, maxval=1 - 1e-6)
        l = jnp.log(u) - jnp.log1p(-u)
        return _wrap(jax.nn.sigmoid((self.logits + l) / temperature))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(v * jax.nn.log_sigmoid(self.logits) + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        return _wrap(-(jsp.xlogy(p, p) + jsp.xlogy(1 - p, 1 - p)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        # paddle's Categorical(logits) treats logits as unnormalized log-probs
        if logits is not None:
            lv = _val(logits)
            self.logits = lv - jsp.logsumexp(lv, -1, keepdims=True)
        elif probs is not None:
            self.logits = jnp.log(_val(probs) / _val(probs).sum(-1, keepdims=True))
        else:
            raise ValueError("pass logits or probs")
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(jax.random.categorical(self._key(), self.logits, shape=shape))

    def log_prob(self, value):
        v = _val(value).astype(jnp.int32)
        return _wrap(jnp.take_along_axis(self.logits, v[..., None], -1)[..., 0])

    def probabilities(self):
        return _wrap(self.probs)

    def entropy(self):
        return _wrap(-(self.probs * self.logits).sum(-1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _val(probs)
        self.probs = p / p.sum(-1, keepdims=True)
        self.logits = jnp.log(self.probs)
        super().__init__(p.shape[:-1], p.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = _shape(shape)
        draws = jax.random.categorical(
            self._key(), self.logits, shape=(self.total_count,) + shape + self.batch_shape
        )
        k = self.event_shape[0]
        counts = jax.nn.one_hot(draws, k).sum(0)
        return _wrap(counts)

    def log_prob(self, value):
        v = _val(value)
        coeff = jsp.gammaln(jnp.asarray(self.total_count + 1.0)) - jsp.gammaln(v + 1).sum(-1)
        return _wrap(coeff + jsp.xlogy(v, self.probs).sum(-1))


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _val(total_count)
        self.probs = _val(probs)
        super().__init__(jnp.broadcast_shapes(jnp.shape(self.total_count), self.probs.shape))

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(
            jax.random.binomial(
                self._key(), jnp.broadcast_to(self.total_count, shape), self.probs
            )
        )

    def log_prob(self, value):
        v = _val(value)
        n, p = self.total_count, self.probs
        coeff = jsp.gammaln(n + 1) - jsp.gammaln(v + 1) - jsp.gammaln(n - v + 1)
        return _wrap(coeff + jsp.xlogy(v, p) + jsp.xlogy(n - v, 1 - p))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k in {0,1,...} (paddle counts failures)."""

    def __init__(self, probs, name=None):
        self.probs = _val(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return _wrap((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return _wrap((1 - self.probs) / self.probs**2)

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=1e-7, maxval=1 - 1e-7)
        return _wrap(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def entropy(self):
        p = self.probs
        return _wrap(-(jsp.xlogy(1 - p, 1 - p) + jsp.xlogy(p, p)) / p)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _wrap(self.rate)

    @property
    def variance(self):
        return _wrap(self.rate)

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(jax.random.poisson(self._key(), self.rate, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(jsp.xlogy(v, self.rate) - self.rate - jsp.gammaln(v + 1))


# ---------------------------------------------------------------------------
# Wrappers
# ---------------------------------------------------------------------------
class Independent(Distribution):
    """Reinterpret the rightmost `reinterpreted_batch_rank` batch dims as
    event dims (log_prob sums over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        super().__init__(bs[: len(bs) - self.rank], bs[len(bs) - self.rank :] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = _val(self.base.log_prob(value))
        return _wrap(lp.sum(tuple(range(lp.ndim - self.rank, lp.ndim))))

    def entropy(self):
        e = _val(self.base.entropy())
        return _wrap(e.sum(tuple(range(e.ndim - self.rank, e.ndim))))


# ---------------------------------------------------------------------------
# Transforms (reference: distribution/transform.py)
# ---------------------------------------------------------------------------
class Transform:
    def forward(self, x):
        return _wrap(self._forward(_val(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_val(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._fldj(_val(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _val(y)
        return _wrap(-self._fldj(self._inverse(yv)))


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2 * (math.log(2) - x - jax.nn.softplus(-2 * x))


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _val(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class StackTransform(Transform):
    """Apply one transform per slice along ``axis`` (reference
    ``paddle.distribution.StackTransform``)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _split(self, x):
        return [jnp.squeeze(s, self.axis) for s in
                jnp.split(x, len(self.transforms), axis=self.axis)]

    def _forward(self, x):
        return jnp.stack([t._forward(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)

    def _inverse(self, y):
        return jnp.stack([t._inverse(s) for t, s in
                          zip(self.transforms, self._split(y))], self.axis)

    def _fldj(self, x):
        return jnp.stack([t._fldj(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)


class StickBreakingTransform(Transform):
    """Unconstrained R^k -> open (k+1)-simplex via stick-breaking
    (reference ``paddle.distribution.StickBreakingTransform``)."""

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        cumprod = jnp.cumprod(1 - z, axis=-1)
        head = z * jnp.concatenate(
            [jnp.ones_like(z[..., :1]), cumprod[..., :-1]], axis=-1)
        return jnp.concatenate([head, cumprod[..., -1:]], axis=-1)

    def _inverse(self, y):
        k = y.shape[-1] - 1
        cum = jnp.cumsum(y[..., :-1], axis=-1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros_like(cum[..., :1]), cum[..., :-1]], axis=-1)
        z = y[..., :-1] / jnp.maximum(rest, 1e-30)
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _fldj(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        t = x - offset
        z = jax.nn.sigmoid(t)
        cumprod = jnp.cumprod(1 - z, axis=-1)
        stick = jnp.concatenate(
            [jnp.ones_like(z[..., :1]), cumprod[..., :-1]], axis=-1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(stick), axis=-1)


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(transforms) if len(transforms) != 1 else transforms[0]
        super().__init__(base.batch_shape, base.event_shape)

    def rsample(self, shape=()):
        x = _val(self.base.rsample(shape))
        return _wrap(self.transform._forward(x))

    def sample(self, shape=()):
        return _wrap(jax.lax.stop_gradient(_val(self.rsample(shape))))

    def log_prob(self, value):
        yv = _val(value)
        x = self.transform._inverse(yv)
        return _wrap(_val(self.base.log_prob(x)) - self.transform._fldj(x))


# ---------------------------------------------------------------------------
# Round-3 additions (reference: distribution/chi2.py, continuous_bernoulli.py,
# exponfamily.py, lkj_cholesky.py, multivariate_normal.py, von_mises.py)
# ---------------------------------------------------------------------------
class ExponentialFamily(Distribution):
    """Natural-parameter base class (reference: distribution/exponfamily.py).
    Subclasses expose `_natural_parameters` and `_log_normalizer`; entropy
    falls out via the Bregman identity, differentiated by jax.grad instead
    of the reference's autograd-graph walk."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        """E[log h(X)] for carrier measure h — 0 when h is folded into the
        log-normalizer (the upstream convention)."""
        return 0.0

    def entropy(self):
        nat = [jnp.asarray(v, jnp.float32) for v in self._natural_parameters]
        lg, grads = jax.value_and_grad(
            lambda *ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=tuple(range(len(nat))))(*nat)
        ent = -self._mean_carrier_measure
        ent = ent + self._log_normalizer(*nat)
        for np_, g in zip(nat, grads):
            ent = ent - np_ * g
        return _wrap(ent)


class Chi2(Gamma):
    """Chi-squared with `df` degrees of freedom = Gamma(df/2, 1/2)
    (reference: distribution/chi2.py)."""

    def __init__(self, df, name=None):
        df = _val(df)
        super().__init__(df / 2.0, jnp.full_like(jnp.asarray(df, jnp.float32), 0.5))

    @property
    def df(self):
        return _wrap(self.concentration * 2)


class ContinuousBernoulli(Distribution):
    """Continuous Bernoulli on [0, 1] (reference:
    distribution/continuous_bernoulli.py; Loaiza-Ganem & Cunningham 2019).
    log C(p) uses the stable tanh^-1 form away from p=0.5 and a Taylor
    expansion inside |p-0.5|<eps (lims trick, as upstream)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = jnp.clip(_val(probs), 1e-6, 1 - 1e-6)
        self._lims = lims
        super().__init__(jnp.asarray(self.probs).shape)

    def _outside(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm(self):
        p_safe = jnp.where(self._outside(), self.probs, 0.25)
        log_c = jnp.log(2 * jnp.arctanh(1 - 2 * p_safe) / (1 - 2 * p_safe))
        x = self.probs - 0.5
        taylor = math.log(2.0) + (4.0 / 3.0) * x**2 + (104.0 / 45.0) * x**4
        return jnp.where(self._outside(), log_c, taylor)

    @property
    def mean(self):
        p = self.probs
        p_safe = jnp.where(self._outside(), p, 0.25)
        m = p_safe / (2 * p_safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * p_safe))
        x = p - 0.5
        taylor = 0.5 + x / 3.0 + (16.0 / 45.0) * x**3
        return _wrap(jnp.where(self._outside(), m, taylor))

    @property
    def variance(self):
        p = self.probs
        p_safe = jnp.where(self._outside(), p, 0.25)
        v = p_safe * (p_safe - 1) / (1 - 2 * p_safe) ** 2 + 1 / (
            2 * jnp.arctanh(1 - 2 * p_safe)) ** 2
        x = p - 0.5
        taylor = 1.0 / 12.0 - (2.0 / 15.0) * x**2
        return _wrap(jnp.where(self._outside(), v, taylor))

    def log_prob(self, value):
        v = _val(value)
        p = self.probs
        return _wrap(v * jnp.log(p) + (1 - v) * jnp.log1p(-p) + self._log_norm())

    def rsample(self, shape=()):
        # inverse-CDF transform of a uniform (reparameterized)
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, jnp.float32,
                               minval=1e-6, maxval=1 - 1e-6)
        p = jnp.broadcast_to(self.probs, shape)
        outside = (p < self._lims[0]) | (p > self._lims[1])
        p_safe = jnp.where(outside, p, 0.25)
        icdf = jnp.log1p(u * (2 * p_safe - 1) / (1 - p_safe)) / (
            jnp.log(p_safe) - jnp.log1p(-p_safe))
        return _wrap(jnp.where(outside, icdf, u))

    def entropy(self):
        # E[-log p(X)] in closed form via mean
        m = _val(self.mean)
        p = self.probs
        return _wrap(-(m * jnp.log(p) + (1 - m) * jnp.log1p(-p)
                       + self._log_norm()))


class MultivariateNormal(Distribution):
    """Multivariate normal via a Cholesky parameterization (reference:
    distribution/multivariate_normal.py). Accepts covariance_matrix,
    precision_matrix, or scale_tril; all solves/log-dets run on the
    triangular factor (one MXU-friendly trsm per op)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = jnp.asarray(_val(loc), jnp.float32)
        given = [a is not None for a in
                 (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("pass exactly one of covariance_matrix / "
                             "precision_matrix / scale_tril")
        if scale_tril is not None:
            self.scale_tril = jnp.asarray(_val(scale_tril), jnp.float32)
        elif covariance_matrix is not None:
            self.scale_tril = jnp.linalg.cholesky(
                jnp.asarray(_val(covariance_matrix), jnp.float32))
        else:
            prec = jnp.asarray(_val(precision_matrix), jnp.float32)
            lp = jnp.linalg.cholesky(prec)
            eye = jnp.eye(prec.shape[-1], dtype=jnp.float32)
            # cov = P^-1 = (Lp Lp^T)^-1; its Cholesky solves from Lp
            self.scale_tril = jnp.linalg.cholesky(
                jax.scipy.linalg.cho_solve((lp, True), eye))
        d = self.scale_tril.shape[-1]
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self.scale_tril.shape[:-2])
        super().__init__(batch, (d,))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape + self.event_shape))

    @property
    def covariance_matrix(self):
        return _wrap(self.scale_tril @ jnp.swapaxes(self.scale_tril, -1, -2))

    @property
    def variance(self):
        v = jnp.sum(self.scale_tril**2, axis=-1)
        return _wrap(jnp.broadcast_to(v, self.batch_shape + self.event_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(self._key(), shape, jnp.float32)
        return _wrap(self.loc + jnp.einsum("...ij,...j->...i", self.scale_tril, eps))

    def log_prob(self, value):
        v = _val(value)
        diff = v - self.loc
        # trsm does not broadcast batch dims; broadcast the factor explicitly
        Lb = jnp.broadcast_to(
            self.scale_tril, diff.shape[:-1] + self.scale_tril.shape[-2:])
        sol = jax.scipy.linalg.solve_triangular(
            Lb, diff[..., None], lower=True)[..., 0]
        m = jnp.sum(sol**2, axis=-1)
        half_logdet = jnp.sum(
            jnp.log(jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), -1)
        d = self.event_shape[0]
        return _wrap(-0.5 * (m + d * math.log(2 * math.pi)) - half_logdet)

    def entropy(self):
        d = self.event_shape[0]
        half_logdet = jnp.sum(
            jnp.log(jnp.diagonal(self.scale_tril, axis1=-2, axis2=-1)), -1)
        out = 0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet
        return _wrap(jnp.broadcast_to(out, self.batch_shape))


class VonMises(Distribution):
    """von Mises circular distribution (reference: distribution/von_mises.py).
    Sampling: Best-Fisher rejection, run as a fixed-round lax.while-free
    masked loop (8 proposal rounds accept >1-1e-6 of mass for kappa<=1e3) —
    the TPU-shaped form of upstream's do-while."""

    def __init__(self, loc, concentration, name=None):
        self.loc = jnp.asarray(_val(loc), jnp.float32)
        self.concentration = jnp.asarray(_val(concentration), jnp.float32)
        super().__init__(
            jnp.broadcast_shapes(self.loc.shape, self.concentration.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        k = self.concentration
        r = jsp.i1e(k) / jsp.i0e(k)
        return _wrap(jnp.broadcast_to(1 - r, self.batch_shape))

    def log_prob(self, value):
        v = _val(value)
        k = self.concentration
        # log I0(k) = log i0e(k) + k (scaled Bessel keeps large-k finite)
        return _wrap(k * jnp.cos(v - self.loc) - math.log(2 * math.pi)
                     - (jnp.log(jsp.i0e(k)) + k))

    def entropy(self):
        k = self.concentration
        r = jsp.i1e(k) / jsp.i0e(k)
        out = -k * r + math.log(2 * math.pi) + jnp.log(jsp.i0e(k)) + k
        return _wrap(jnp.broadcast_to(out, self.batch_shape))

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        k = jnp.broadcast_to(jnp.maximum(self.concentration, 1e-5), shape)
        tau = 1 + jnp.sqrt(1 + 4 * k**2)
        rho = (tau - jnp.sqrt(2 * tau)) / (2 * k)
        r = (1 + rho**2) / (2 * rho)
        key = self._key()
        out = jnp.zeros(shape, jnp.float32)
        done = jnp.zeros(shape, bool)
        for i in range(8):  # masked rejection rounds
            k1, k2, k3, key = jax.random.split(key, 4)
            u1 = jax.random.uniform(k1, shape)
            u2 = jax.random.uniform(k2, shape)
            u3 = jax.random.uniform(k3, shape)
            z = jnp.cos(math.pi * u1)
            f = (1 + r * z) / (r + z)
            c = k * (r - f)
            accept = (c * (2 - c) - u2 > 0) | (jnp.log(c / u2) + 1 - c >= 0)
            val = jnp.sign(u3 - 0.5) * jnp.arccos(jnp.clip(f, -1, 1))
            out = jnp.where(done, out, val)
            done = done | accept
        ang = self.loc + out
        return _wrap(jnp.arctan2(jnp.sin(ang), jnp.cos(ang)))  # wrap to (-pi, pi]


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices (reference:
    distribution/lkj_cholesky.py). Sampling via the onion construction
    (vectorized over rows); log_prob = sum_i (d - i - 1 + 2(eta - 1))
    * log L_ii + log-normalizer."""

    def __init__(self, dim, concentration=1.0, sample_method="onion", name=None):
        self.dim = int(dim)
        self.concentration = jnp.asarray(_val(concentration), jnp.float32)
        self.sample_method = sample_method
        super().__init__(jnp.asarray(self.concentration).shape,
                         (self.dim, self.dim))

    def sample(self, shape=()):
        d = self.dim
        shape = _shape(shape) + self.batch_shape
        eta = jnp.broadcast_to(self.concentration, shape)
        key = self._key()
        kb, kn = jax.random.split(key)
        # onion: row i (i>=1) direction ~ uniform sphere S^{i-1}, radius^2 ~
        # Beta(i/2, alpha_i) with alpha_i = eta + (d - 1 - i)/2
        L = jnp.zeros(shape + (d, d), jnp.float32).at[..., 0, 0].set(1.0)
        for i in range(1, d):
            alpha = eta + (d - 1 - i) / 2.0
            kb, k1, k2 = jax.random.split(kb, 3)
            y = jax.random.beta(k1, i / 2.0, alpha, shape)
            u = jax.random.normal(k2, shape + (i,), jnp.float32)
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.clip(1 - y, 1e-12)))
        return _wrap(L)

    def log_prob(self, value):
        L = _val(value)
        d = self.dim
        eta = self.concentration
        i = jnp.arange(1, d, dtype=jnp.float32)  # rows 1..d-1
        order = d - i - 1 + 2 * (eta[..., None] - 1)
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        unnorm = jnp.sum(order * jnp.log(diag), axis=-1)
        # normalizer (upstream closed form)
        alpha = eta[..., None] + (d - 1 - i) / 2.0
        logz = jnp.sum(
            (i / 2.0) * math.log(math.pi)
            + jsp.gammaln(alpha)
            - jsp.gammaln(alpha + i / 2.0),
            axis=-1,
        )
        return _wrap(unnorm - logz)


# ---------------------------------------------------------------------------
# KL divergence registry (reference: distribution/kl.py register_kl)
# ---------------------------------------------------------------------------
_KL_REGISTRY: Dict[Tuple[Type, Type], callable] = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution):
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})"
    )


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    vr = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _wrap(0.5 * (vr + t1 - 1 - jnp.log(vr)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return _wrap(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a, b = p.probs, q.probs
    eps = 1e-7
    return _wrap(
        a * (jnp.log(a + eps) - jnp.log(b + eps))
        + (1 - a) * (jnp.log(1 - a + eps) - jnp.log(1 - b + eps))
    )


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return _wrap((p.probs * (p.logits - q.logits)).sum(-1))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return _wrap(
        jsp.betaln(a2, b2)
        - jsp.betaln(a1, b1)
        + (a1 - a2) * jsp.digamma(a1)
        + (b1 - b2) * jsp.digamma(b1)
        + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1)
    )


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1)
    return _wrap(
        jsp.gammaln(a0)
        - jsp.gammaln(b.sum(-1))
        - (jsp.gammaln(a) - jsp.gammaln(b)).sum(-1)
        + ((a - b) * (jsp.digamma(a) - jsp.digamma(a0)[..., None])).sum(-1)
    )


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
    return _wrap(
        (a1 - a2) * jsp.digamma(a1)
        - jsp.gammaln(a1)
        + jsp.gammaln(a2)
        + a2 * (jnp.log(b1) - jnp.log(b2))
        + a1 * (b2 / b1 - 1)
    )


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return _wrap(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    return _wrap(
        jnp.log(q.scale / p.scale)
        + d / q.scale
        + p.scale / q.scale * jnp.exp(-d / p.scale)
        - 1
    )


__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Laplace", "Gumbel",
    "Cauchy", "Exponential", "Gamma", "Beta", "Dirichlet", "StudentT",
    "Bernoulli", "Categorical", "Multinomial", "Binomial", "Geometric",
    "Poisson", "Independent", "Chi2", "ContinuousBernoulli", "ExponentialFamily", "LKJCholesky", "MultivariateNormal", "VonMises", "TransformedDistribution", "Transform",
    "ExpTransform", "AffineTransform", "SigmoidTransform", "TanhTransform",
    "AbsTransform", "PowerTransform", "ChainTransform", "StackTransform",
    "StickBreakingTransform", "kl_divergence",
    "register_kl",
]
