"""paddle.distribution parity — probability distributions, transforms, KL.

Reference: ``python/paddle/distribution/`` (Distribution base with
sample/rsample/log_prob/entropy, the named distribution family, the Transform
family, and a (p,q)-type-registered ``kl_divergence``). TPU-native design:
every density / sampler is a pure jnp expression over ``jax.random`` keys
(drawn from the framework RNG so sampling is reproducible under seed() and
traceable under jit), so distributions compose with jit/vmap/grad — reparam
(rsample) gradients come for free from the functional form.
"""
from __future__ import annotations

import math
from typing import Dict, Tuple, Type

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..framework import rng as _rng
from ..framework.core import Tensor


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) else x


def _wrap(v):
    return Tensor(v)


def _shape(sample_shape):
    if sample_shape is None:
        return ()
    if isinstance(sample_shape, int):
        return (sample_shape,)
    return tuple(int(s) for s in sample_shape)


class Distribution:
    """Base class (reference: distribution/distribution.py)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        # default: sampling without grad = rsample with stopped gradient
        return _wrap(jax.lax.stop_gradient(_val(self.rsample(shape))))

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _wrap(jnp.exp(_val(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _key(self):
        return _rng.next_key()


# ---------------------------------------------------------------------------
# Continuous
# ---------------------------------------------------------------------------
class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale**2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(self.scale, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        dtype = jnp.result_type(self.loc, self.scale)
        if not jnp.issubdtype(dtype, jnp.floating):
            dtype = jnp.float32
        eps = jax.random.normal(self._key(), shape, dtype)
        return _wrap(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _val(value)
        var = self.scale**2
        return _wrap(
            -((v - self.loc) ** 2) / (2 * var)
            - jnp.log(self.scale)
            - 0.5 * math.log(2 * math.pi)
        )

    def entropy(self):
        out = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return _wrap(jnp.broadcast_to(out, self.batch_shape))

    def cdf(self, value):
        v = _val(value)
        return _wrap(0.5 * (1 + jsp.erf((v - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, q):
        qv = _val(q)
        return _wrap(self.loc + self.scale * math.sqrt(2) * jsp.erfinv(2 * qv - 1))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return _wrap(jnp.exp(self.loc + self.scale**2 / 2))

    @property
    def variance(self):
        s2 = self.scale**2
        return _wrap((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def rsample(self, shape=()):
        return _wrap(jnp.exp(_val(self._base.rsample(shape))))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(_val(self._base.log_prob(jnp.log(v))) - jnp.log(v))

    def entropy(self):
        return _wrap(_val(self._base.entropy()) + self.loc)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return _wrap((self.low + self.high) / 2)

    @property
    def variance(self):
        return _wrap((self.high - self.low) ** 2 / 12)

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape)
        return _wrap(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _val(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _wrap(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.high - self.low), self.batch_shape))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(2 * self.scale**2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(math.sqrt(2) * self.scale, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=-0.5, maxval=0.5)
        return _wrap(self.loc - self.scale * jnp.sign(u) * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(-jnp.abs(v - self.loc) / self.scale - jnp.log(2 * self.scale))

    def entropy(self):
        return _wrap(jnp.broadcast_to(1 + jnp.log(2 * self.scale), self.batch_shape))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc + self.scale * jnp.euler_gamma, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(math.pi**2 / 6 * self.scale**2, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        g = jax.random.gumbel(self._key(), shape)
        return _wrap(self.loc + self.scale * g)

    def log_prob(self, value):
        z = (_val(value) - self.loc) / self.scale
        return _wrap(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.scale) + 1 + jnp.euler_gamma, self.batch_shape))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=1e-7, maxval=1 - 1e-7)
        return _wrap(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    def log_prob(self, value):
        z = (_val(value) - self.loc) / self.scale
        return _wrap(-math.log(math.pi) - jnp.log(self.scale) - jnp.log1p(z**2))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(4 * math.pi * self.scale), self.batch_shape))

    def cdf(self, value):
        z = (_val(value) - self.loc) / self.scale
        return _wrap(jnp.arctan(z) / math.pi + 0.5)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _wrap(1 / self.rate)

    @property
    def variance(self):
        return _wrap(1 / self.rate**2)

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        e = jax.random.exponential(self._key(), shape)
        return _wrap(e / self.rate)

    def log_prob(self, value):
        v = _val(value)
        return _wrap(jnp.where(v >= 0, jnp.log(self.rate) - self.rate * v, -jnp.inf))

    def entropy(self):
        return _wrap(1 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _val(concentration)
        self.rate = _val(rate)
        super().__init__(
            jnp.broadcast_shapes(self.concentration.shape, self.rate.shape)
        )

    @property
    def mean(self):
        return _wrap(self.concentration / self.rate)

    @property
    def variance(self):
        return _wrap(self.concentration / self.rate**2)

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        g = jax.random.gamma(self._key(), jnp.broadcast_to(self.concentration, shape))
        return _wrap(g / self.rate)

    def log_prob(self, value):
        v = _val(value)
        a, b = self.concentration, self.rate
        return _wrap(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v - jsp.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return _wrap(a - jnp.log(b) + jsp.gammaln(a) + (1 - a) * jsp.digamma(a))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return _wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(self.alpha * self.beta / (s**2 * (s + 1)))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        k1, k2 = jax.random.split(self._key())
        ga = jax.random.gamma(k1, jnp.broadcast_to(self.alpha, shape))
        gb = jax.random.gamma(k2, jnp.broadcast_to(self.beta, shape))
        return _wrap(ga / (ga + gb))

    def log_prob(self, value):
        v = _val(value)
        a, b = self.alpha, self.beta
        return _wrap((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - jsp.betaln(a, b))

    def entropy(self):
        a, b = self.alpha, self.beta
        return _wrap(
            jsp.betaln(a, b)
            - (a - 1) * jsp.digamma(a)
            - (b - 1) * jsp.digamma(b)
            + (a + b - 2) * jsp.digamma(a + b)
        )


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.concentration / self.concentration.sum(-1, keepdims=True))

    @property
    def variance(self):
        a = self.concentration
        a0 = a.sum(-1, keepdims=True)
        return _wrap(a * (a0 - a) / (a0**2 * (a0 + 1)))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape + self.event_shape
        g = jax.random.gamma(self._key(), jnp.broadcast_to(self.concentration, shape))
        return _wrap(g / g.sum(-1, keepdims=True))

    def log_prob(self, value):
        v = _val(value)
        a = self.concentration
        norm = jsp.gammaln(a.sum(-1)) - jsp.gammaln(a).sum(-1)
        return _wrap(((a - 1) * jnp.log(v)).sum(-1) + norm)

    def entropy(self):
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        lnB = jsp.gammaln(a).sum(-1) - jsp.gammaln(a0)
        return _wrap(
            lnB
            + (a0 - k) * jsp.digamma(a0)
            - ((a - 1) * jsp.digamma(a)).sum(-1)
        )


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _val(df)
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(
            jnp.broadcast_shapes(self.df.shape, self.loc.shape, self.scale.shape)
        )

    @property
    def mean(self):
        return _wrap(jnp.where(self.df > 1, jnp.broadcast_to(self.loc, self.batch_shape), jnp.nan))

    @property
    def variance(self):
        v = jnp.where(
            self.df > 2,
            self.scale**2 * self.df / (self.df - 2),
            jnp.where(self.df > 1, jnp.inf, jnp.nan),
        )
        return _wrap(jnp.broadcast_to(v, self.batch_shape))

    def rsample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        t = jax.random.t(self._key(), jnp.broadcast_to(self.df, shape), shape)
        return _wrap(self.loc + self.scale * t)

    def log_prob(self, value):
        z = (_val(value) - self.loc) / self.scale
        df = self.df
        return _wrap(
            jsp.gammaln((df + 1) / 2)
            - jsp.gammaln(df / 2)
            - 0.5 * jnp.log(df * math.pi)
            - jnp.log(self.scale)
            - (df + 1) / 2 * jnp.log1p(z**2 / df)
        )


# ---------------------------------------------------------------------------
# Discrete
# ---------------------------------------------------------------------------
class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _val(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _val(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return _wrap(self.probs)

    @property
    def variance(self):
        return _wrap(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(
            jax.random.bernoulli(self._key(), self.probs, shape).astype(jnp.float32)
        )

    def rsample(self, shape=(), temperature=1.0):
        # relaxed Bernoulli (Gumbel-sigmoid), matching paddle's rsample
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=1e-6, maxval=1 - 1e-6)
        l = jnp.log(u) - jnp.log1p(-u)
        return _wrap(jax.nn.sigmoid((self.logits + l) / temperature))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(v * jax.nn.log_sigmoid(self.logits) + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        return _wrap(-(jsp.xlogy(p, p) + jsp.xlogy(1 - p, 1 - p)))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        # paddle's Categorical(logits) treats logits as unnormalized log-probs
        if logits is not None:
            lv = _val(logits)
            self.logits = lv - jsp.logsumexp(lv, -1, keepdims=True)
        elif probs is not None:
            self.logits = jnp.log(_val(probs) / _val(probs).sum(-1, keepdims=True))
        else:
            raise ValueError("pass logits or probs")
        self.probs = jnp.exp(self.logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(jax.random.categorical(self._key(), self.logits, shape=shape))

    def log_prob(self, value):
        v = _val(value).astype(jnp.int32)
        return _wrap(jnp.take_along_axis(self.logits, v[..., None], -1)[..., 0])

    def probabilities(self):
        return _wrap(self.probs)

    def entropy(self):
        return _wrap(-(self.probs * self.logits).sum(-1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _val(probs)
        self.probs = p / p.sum(-1, keepdims=True)
        self.logits = jnp.log(self.probs)
        super().__init__(p.shape[:-1], p.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = _shape(shape)
        draws = jax.random.categorical(
            self._key(), self.logits, shape=(self.total_count,) + shape + self.batch_shape
        )
        k = self.event_shape[0]
        counts = jax.nn.one_hot(draws, k).sum(0)
        return _wrap(counts)

    def log_prob(self, value):
        v = _val(value)
        coeff = jsp.gammaln(jnp.asarray(self.total_count + 1.0)) - jsp.gammaln(v + 1).sum(-1)
        return _wrap(coeff + jsp.xlogy(v, self.probs).sum(-1))


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _val(total_count)
        self.probs = _val(probs)
        super().__init__(jnp.broadcast_shapes(jnp.shape(self.total_count), self.probs.shape))

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(
            jax.random.binomial(
                self._key(), jnp.broadcast_to(self.total_count, shape), self.probs
            )
        )

    def log_prob(self, value):
        v = _val(value)
        n, p = self.total_count, self.probs
        coeff = jsp.gammaln(n + 1) - jsp.gammaln(v + 1) - jsp.gammaln(n - v + 1)
        return _wrap(coeff + jsp.xlogy(v, p) + jsp.xlogy(n - v, 1 - p))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k in {0,1,...} (paddle counts failures)."""

    def __init__(self, probs, name=None):
        self.probs = _val(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return _wrap((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return _wrap((1 - self.probs) / self.probs**2)

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), shape, minval=1e-7, maxval=1 - 1e-7)
        return _wrap(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def entropy(self):
        p = self.probs
        return _wrap(-(jsp.xlogy(1 - p, 1 - p) + jsp.xlogy(p, p)) / p)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _wrap(self.rate)

    @property
    def variance(self):
        return _wrap(self.rate)

    def sample(self, shape=()):
        shape = _shape(shape) + self.batch_shape
        return _wrap(jax.random.poisson(self._key(), self.rate, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _val(value)
        return _wrap(jsp.xlogy(v, self.rate) - self.rate - jsp.gammaln(v + 1))


# ---------------------------------------------------------------------------
# Wrappers
# ---------------------------------------------------------------------------
class Independent(Distribution):
    """Reinterpret the rightmost `reinterpreted_batch_rank` batch dims as
    event dims (log_prob sums over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        super().__init__(bs[: len(bs) - self.rank], bs[len(bs) - self.rank :] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = _val(self.base.log_prob(value))
        return _wrap(lp.sum(tuple(range(lp.ndim - self.rank, lp.ndim))))

    def entropy(self):
        e = _val(self.base.entropy())
        return _wrap(e.sum(tuple(range(e.ndim - self.rank, e.ndim))))


# ---------------------------------------------------------------------------
# Transforms (reference: distribution/transform.py)
# ---------------------------------------------------------------------------
class Transform:
    def forward(self, x):
        return _wrap(self._forward(_val(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_val(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._fldj(_val(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _val(y)
        return _wrap(-self._fldj(self._inverse(yv)))


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2 * (math.log(2) - x - jax.nn.softplus(-2 * x))


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _val(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(transforms) if len(transforms) != 1 else transforms[0]
        super().__init__(base.batch_shape, base.event_shape)

    def rsample(self, shape=()):
        x = _val(self.base.rsample(shape))
        return _wrap(self.transform._forward(x))

    def sample(self, shape=()):
        return _wrap(jax.lax.stop_gradient(_val(self.rsample(shape))))

    def log_prob(self, value):
        yv = _val(value)
        x = self.transform._inverse(yv)
        return _wrap(_val(self.base.log_prob(x)) - self.transform._fldj(x))


# ---------------------------------------------------------------------------
# KL divergence registry (reference: distribution/kl.py register_kl)
# ---------------------------------------------------------------------------
_KL_REGISTRY: Dict[Tuple[Type, Type], callable] = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution):
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})"
    )


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    vr = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _wrap(0.5 * (vr + t1 - 1 - jnp.log(vr)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return _wrap(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a, b = p.probs, q.probs
    eps = 1e-7
    return _wrap(
        a * (jnp.log(a + eps) - jnp.log(b + eps))
        + (1 - a) * (jnp.log(1 - a + eps) - jnp.log(1 - b + eps))
    )


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return _wrap((p.probs * (p.logits - q.logits)).sum(-1))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return _wrap(
        jsp.betaln(a2, b2)
        - jsp.betaln(a1, b1)
        + (a1 - a2) * jsp.digamma(a1)
        + (b1 - b2) * jsp.digamma(b1)
        + (a2 - a1 + b2 - b1) * jsp.digamma(a1 + b1)
    )


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1)
    return _wrap(
        jsp.gammaln(a0)
        - jsp.gammaln(b.sum(-1))
        - (jsp.gammaln(a) - jsp.gammaln(b)).sum(-1)
        + ((a - b) * (jsp.digamma(a) - jsp.digamma(a0)[..., None])).sum(-1)
    )


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
    return _wrap(
        (a1 - a2) * jsp.digamma(a1)
        - jsp.gammaln(a1)
        + jsp.gammaln(a2)
        + a2 * (jnp.log(b1) - jnp.log(b2))
        + a1 * (b2 / b1 - 1)
    )


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return _wrap(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    return _wrap(
        jnp.log(q.scale / p.scale)
        + d / q.scale
        + p.scale / q.scale * jnp.exp(-d / p.scale)
        - 1
    )


__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Laplace", "Gumbel",
    "Cauchy", "Exponential", "Gamma", "Beta", "Dirichlet", "StudentT",
    "Bernoulli", "Categorical", "Multinomial", "Binomial", "Geometric",
    "Poisson", "Independent", "TransformedDistribution", "Transform",
    "ExpTransform", "AffineTransform", "SigmoidTransform", "TanhTransform",
    "AbsTransform", "PowerTransform", "ChainTransform", "kl_divergence",
    "register_kl",
]
