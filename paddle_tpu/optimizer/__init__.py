"""Optimizers (paddle.optimizer parity).

Reference: ``python/paddle/optimizer/`` — SGD/Momentum/Adagrad/Adam/AdamW/
Adamax/Lamb/RMSProp, LRScheduler family, grad clip (SURVEY.md §2.2).

TPU-native design: each optimizer's math is one pure jnp update rule
(`_rule`). The eager ``step()`` applies it per parameter (like the reference's
per-param adam op); ``paddle_tpu.jit.TrainStep`` calls the same rule inside
the compiled train step, where XLA fuses all parameter updates into one
program (the reference needs a separate fused multi_tensor_adam for this —
here it falls out of compilation).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as _dtypes
from ..framework.core import Tensor, no_grad
from ..framework.op import raw
from . import lr  # noqa: F401
from .lr import LRScheduler


# ------------------------------------------------------------- grad clip ----
class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, None if g is None else jnp.clip(g, self.min, self.max)) for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, g * scale))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip (reference: ClipGradByGlobalNorm — the hybrid-parallel
    default). Under SPMD the norm over sharded grads is computed by XLA with
    an implicit all-reduce."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for p, g in params_grads if g is not None]
        if not sq:
            return params_grads
        gnorm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return [(p, None if g is None else (g * scale).astype(g.dtype)) for p, g in params_grads]


# ------------------------------------------------------------ regularizer ----
class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, p, g):
        return g + self.coeff * p


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __call__(self, p, g):
        return g + self.coeff * jnp.sign(p)


# --------------------------------------------------------------- optimizer --
class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None, name=None):
        if parameters is None:
            raise ValueError("parameters must be provided (list of Parameters)")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (float, int)):
            self._regularizer = L2Decay(float(weight_decay))
            self._coupled_wd = None
        else:
            self._regularizer = weight_decay  # L1Decay/L2Decay instance or None
            self._coupled_wd = None
        self._accumulators: List[dict] = [None] * len(self._parameter_list)
        self._use_master_weights = False
        self._master = {}

    # -- lr ----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("set_lr cannot be used with an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state -------------------------------------------------------------
    def _init_state(self, p) -> dict:
        return {}

    def _rule(self, p, g, st, lr):
        """Pure update rule: (param, grad, state, lr) -> (new_param, new_state)."""
        raise NotImplementedError

    # -- eager step (DyGraph parity: reads .grad, updates in place) ---------
    @no_grad()
    def step(self):
        pg = [(p, raw(p.grad) if p.grad is not None else None) for p in self._parameter_list if p.trainable]
        if self._grad_clip is not None:
            vals = [(raw(p), g) for p, g in pg]
            clipped = self._grad_clip(vals)
            pg = [(p, cg) for (p, _), (_, cg) in zip(pg, clipped)]
        lr = self.get_lr()
        grad_by_id = {id(q): gg for q, gg in pg}
        for i, p in enumerate(self._parameter_list):
            if not p.trainable:
                continue
            g = grad_by_id.get(id(p))
            if g is None:
                continue
            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
            if self._accumulators[i] is None:
                self._accumulators[i] = self._init_state(p)
            pv = raw(p)
            if self._use_master_weights and pv.dtype != jnp.float32:
                mv = self._master.get(i)
                if mv is None:
                    mv = pv.astype(jnp.float32)
                g32 = g.astype(jnp.float32)
                g32 = self._apply_decay(mv, g32, p)
                new_m, self._accumulators[i] = self._rule(mv, g32, self._accumulators[i], plr)
                self._master[i] = new_m
                p._rebind(new_m.astype(pv.dtype))
            else:
                g = self._apply_decay(pv, g.astype(pv.dtype), p)
                new_p, self._accumulators[i] = self._rule(pv, g, self._accumulators[i], plr)
                p._rebind(new_p)

    def _apply_decay(self, pv, g, p):
        reg = p.regularizer or self._regularizer
        if reg is not None:
            g = reg(pv, g)
        return g

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    # -- functional step (used by paddle_tpu.jit.TrainStep) -----------------
    def functional_states(self):
        for i, p in enumerate(self._parameter_list):
            if self._accumulators[i] is None:
                self._accumulators[i] = self._init_state(p)
        return list(self._accumulators)

    def load_functional_states(self, states):
        self._accumulators = list(states)

    def functional_step(self, param_vals, grad_vals, states, lr):
        """Pure: lists of values -> (new_params, new_states). No side effects."""
        if self._grad_clip is not None:
            clipped = self._grad_clip(list(zip(param_vals, grad_vals)))
            grad_vals = [g for _, g in clipped]
        return self.functional_update(param_vals, grad_vals, states, lr)

    def functional_update(self, param_vals, grad_vals, states, lr):
        """functional_step minus grad clip: the raw per-parameter rule.
        Distributed callers that clip on a different data layout (e.g. the
        ZeRO shard-local update in fleet, where the global norm is a scalar
        psum over shard blocks) clip first, then call this directly."""
        new_ps, new_sts = [], []
        for p, pv, g, st in zip(self._parameter_list, param_vals, grad_vals, states):
            if g is None or not p.trainable:
                new_ps.append(pv)
                new_sts.append(st)
                continue
            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
            g = self._apply_decay(pv, g.astype(pv.dtype), p)
            np_, nst = self._rule(pv, g, st, plr)
            new_ps.append(np_)
            new_sts.append(nst)
        return new_ps, new_sts

    # -- serialization -------------------------------------------------------
    def state_dict(self):
        out = {}
        for i, st in enumerate(self._accumulators):
            if st is None:
                continue
            name = self._parameter_list[i].name or f"param_{i}"
            for k, v in st.items():
                # COPY array leaves: under TrainStep the live state buffers
                # are donated to the next compiled step, which would delete
                # a by-reference checkpoint out from under the caller
                out[f"{name}.{k}"] = (
                    v if isinstance(v, (int, float)) else Tensor(jnp.array(v))
                )
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        sched_state = state.get("LR_Scheduler")
        if sched_state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(sched_state)
        for i, p in enumerate(self._parameter_list):
            name = p.name or f"param_{i}"
            st = self._init_state(p)
            found = False
            for k in list(st):
                key = f"{name}.{k}"
                if key in state:
                    v = state[key]
                    st[k] = raw(v) if isinstance(v, Tensor) else v
                    found = True
            if found:
                self._accumulators[i] = st


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._use_master_weights = bool(multi_precision)

    def _rule(self, p, g, st, lr):
        return p - lr * g, st


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None, use_nesterov=False,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov
        self._use_master_weights = bool(multi_precision)

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(raw(p))}

    def _rule(self, p, g, st, lr):
        v = self._momentum * st["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(raw(p), self._init_acc)}

    def _rule(self, p, g, st, lr):
        m = st["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._use_master_weights = multi_precision

    def _init_state(self, p):
        pv = raw(p)
        dt = jnp.float32 if self._use_master_weights else pv.dtype
        return {
            "moment1": jnp.zeros(pv.shape, dt),
            "moment2": jnp.zeros(pv.shape, dt),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _rule(self, p, g, st, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = st["beta1_pow"] * b1
        b2p = st["beta2_pow"] * b2
        m1 = b1 * st["moment1"] + (1 - b1) * g
        m2 = b2 * st["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m1 / (1 - b1p)
        vhat = m2 / (1 - b2p)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p.astype(p.dtype), {"moment1": m1, "moment2": m2, "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip,
                         lazy_mode, multi_precision, name)
        self._wd = float(weight_decay) if isinstance(weight_decay, (int, float)) else 0.01
        self._apply_decay_fn = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        self._decay_skip = set()
        if apply_decay_param_fun is not None:
            for p in self._parameter_list:
                if not apply_decay_param_fun(p.name or ""):
                    self._decay_skip.add(id(p))

    def functional_step(self, param_vals, grad_vals, states, lr):
        # decoupled decay folded into _rule via closure over per-call flag
        return super().functional_step(param_vals, grad_vals, states, lr)

    def _rule(self, p, g, st, lr):
        decay = getattr(self, "_current_decay", self._wd)
        if decay:
            p = p * (1.0 - lr * decay)
        return super()._rule(p, g, st, lr)

    @no_grad()
    def step(self):
        # set per-param decay flags around the base step
        base_step = super().step
        orig = self._wd
        # base class handles the loop; per-param skip via _current_decay
        # simplest: temporarily zero decay for skipped params by monkey flag
        if not self._decay_skip:
            base_step()
            return
        # slow path with per-param decay decisions
        for i, p in enumerate(self._parameter_list):
            self._current_decay = 0.0 if id(p) in self._decay_skip else self._wd
            # apply one-param step by faking a single-item list
            if p.grad is None or not p.trainable:
                continue
            if self._accumulators[i] is None:
                self._accumulators[i] = self._init_state(p)
            g = raw(p.grad)
            if self._grad_clip is not None:
                g = self._grad_clip([(raw(p), g)])[0][1]
            new_p, self._accumulators[i] = self._rule(raw(p), g.astype(raw(p).dtype), self._accumulators[i], self.get_lr() * p.optimize_attr.get("learning_rate", 1.0))
            p._rebind(new_p)
        self._current_decay = orig


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        pv = raw(p)
        return {"moment": jnp.zeros_like(pv), "inf_norm": jnp.zeros_like(pv), "beta1_pow": jnp.ones((), jnp.float32)}

    def _rule(self, p, g, st, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = st["beta1_pow"] * b1
        m = b1 * st["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * st["inf_norm"], jnp.abs(g) + eps)
        new_p = p - (lr / (1 - b1p)) * m / u
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _init_state(self, p):
        pv = raw(p)
        st = {"mean_square": jnp.zeros_like(pv), "velocity": jnp.zeros_like(pv)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(pv)
        return st

    def _rule(self, p, g, st, lr):
        ms = self._rho * st["mean_square"] + (1 - self._rho) * jnp.square(g)
        if self._centered:
            mg = self._rho * st["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._epsilon)
        v = self._momentum * st["velocity"] + lr * g / denom
        new_st = {"mean_square": ms, "velocity": v}
        if mg is not None:
            new_st["mean_grad"] = mg
        return p - v, new_st


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-06, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        pv = raw(p)
        st = {"moment1": jnp.zeros_like(pv), "moment2": jnp.zeros_like(pv),
              "beta1_pow": jnp.ones((), jnp.float32), "beta2_pow": jnp.ones((), jnp.float32)}
        if self._exclude_fn is not None and self._exclude_fn(p.name or ""):
            # jit-static exclusion marker (pytree structure, not a bool
            # leaf — see Lars._init_state)
            st["wd_excluded"] = ()
        return st

    def _rule(self, p, g, st, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd = 0.0 if "wd_excluded" in st else self._wd
        b1p = st["beta1_pow"] * b1
        b2p = st["beta2_pow"] * b2
        m1 = b1 * st["moment1"] + (1 - b1) * g
        m2 = b2 * st["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m1 / (1 - b1p)
        vhat = m2 / (1 - b2p)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, dict(st, moment1=m1, moment2=m2,
                                        beta1_pow=b1p, beta2_pow=b2p)


class Lars(Optimizer):
    """LARS momentum (You et al. 2017): layerwise trust-ratio scaling for
    large-batch training. Reference:
    ``paddle/fluid/optimizer.py::LarsMomentumOptimizer`` /
    ``lars_momentum_op`` (enabled by DistributedStrategy's lars flag).

    local_lr = lr * lars_coeff * ||p|| / (||g|| + lars_weight_decay*||p||)
    v        = momentum * v + local_lr * (g + lars_weight_decay * p)
    p       -= v
    Parameters matched by ``exclude_from_weight_decay`` (substring on the
    param name, as upstream) run with lars_weight_decay = 0 but KEEP the
    trust-ratio local lr (upstream zeroes only the decay term).
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = float(momentum)
        self._coeff = float(lars_coeff)
        self._lars_wd = float(lars_weight_decay)
        self._epsilon = float(epsilon)
        self._exclude = list(exclude_from_weight_decay or [])
        self._use_master_weights = bool(multi_precision)

    def _init_state(self, p):
        st = {"velocity": jnp.zeros_like(raw(p))}
        if any(s in (p.name or "") for s in self._exclude):
            # the exclusion marker must be STATIC under jit (a bool leaf
            # would become a traced array and `if excluded:` would raise
            # TracerBoolConversionError in jit.TrainStep) — encode it as
            # pytree STRUCTURE: an empty-tuple entry carries no leaves but
            # survives the functional state round-trip
            st["excluded"] = ()
        return st

    def _rule(self, p, g, st, lr):
        wd = 0.0 if "excluded" in st else self._lars_wd
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        denom = g_norm + wd * p_norm + self._epsilon
        local_lr = jnp.where(
            (p_norm > 0) & (denom > 0),
            lr * self._coeff * p_norm / denom, lr)
        v = self._momentum * st["velocity"] + local_lr * (g + wd * p)
        return p - v, dict(st, velocity=v)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon

    def _init_state(self, p):
        pv = raw(p)
        return {"avg_squared_grad": jnp.zeros_like(pv), "avg_squared_update": jnp.zeros_like(pv)}

    def _rule(self, p, g, st, lr):
        asg = self._rho * st["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        update = -jnp.sqrt((st["avg_squared_update"] + self._epsilon) / (asg + self._epsilon)) * g
        asu = self._rho * st["avg_squared_update"] + (1 - self._rho) * jnp.square(update)
        return p + lr * update, {"avg_squared_grad": asg, "avg_squared_update": asu}


class ASGD(Optimizer):
    """Stochastic Average Gradient descent (reference:
    ``python/paddle/optimizer/asgd.py``). ``d`` holds the running SUM of the
    last ``batch_num`` gradients via a rotating slot buffer ``ys``:
    ``d <- d - ys[t % n] + g; ys[t % n] <- g; param <- param - lr * d / m``
    with ``m`` the number of batches seen, saturating at ``batch_num``.
    The slot write is a ``dynamic_update_slice`` on a state scalar, so the
    rule jits. Memory note (as upstream documents): state is
    ``batch_num x`` the parameter size."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._batch_num = int(batch_num)
        self._use_master_weights = bool(multi_precision)

    def _init_state(self, p):
        pv = raw(p)
        dt = jnp.float32 if self._use_master_weights else pv.dtype
        return {"d": jnp.zeros(pv.shape, dt),
                "ys": jnp.zeros((self._batch_num,) + tuple(pv.shape), dt),
                "step": jnp.zeros((), jnp.int32)}

    def _rule(self, p, g, st, lr):
        slot = st["step"] % self._batch_num
        y_old = jax.lax.dynamic_index_in_dim(st["ys"], slot, 0,
                                             keepdims=False)
        d = st["d"] - y_old + g
        ys = jax.lax.dynamic_update_index_in_dim(st["ys"], g, slot, 0)
        m = jnp.minimum(st["step"] + 1, self._batch_num).astype(p.dtype)
        new_p = p - lr * d / m
        return new_p.astype(p.dtype), {"d": d, "ys": ys,
                                       "step": st["step"] + 1}


class Rprop(Optimizer):
    """Resilient backpropagation (reference: ``python/paddle/optimizer/rprop.py``).
    Maintains a per-element step size that grows by ``etas[1]`` while the
    gradient keeps its sign and shrinks by ``etas[0]`` on a sign flip (the
    flipped gradient is dropped for that element); the update uses only the
    gradient's sign. Batch-size independent — full-batch contract as upstream
    documents."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_range = (float(learning_rate_range[0]), float(learning_rate_range[1]))
        self._etas = (float(etas[0]), float(etas[1]))
        self._use_master_weights = bool(multi_precision)

    def _init_state(self, p):
        pv = raw(p)
        dt = jnp.float32 if self._use_master_weights else pv.dtype
        return {"prev_grad": jnp.zeros(pv.shape, dt),
                "lrs": jnp.full(pv.shape, float(self.get_lr()), dt)}

    def _rule(self, p, g, st, lr):
        sign = jnp.sign(st["prev_grad"] * g)
        lo, hi = self._lr_range
        neg, pos = self._etas
        factor = jnp.where(sign > 0, pos, jnp.where(sign < 0, neg, 1.0))
        lrs = jnp.clip(st["lrs"] * factor, lo, hi)
        g_eff = jnp.where(sign < 0, 0.0, g)  # drop sign-flipped elements
        new_p = p - lrs * jnp.sign(g_eff)
        return new_p.astype(p.dtype), {"prev_grad": g_eff, "lrs": lrs}


class NAdam(Optimizer):
    """Adam with Nesterov momentum and the Dozat momentum schedule
    (reference: ``python/paddle/optimizer/nadam.py``):
    ``mu_t = beta1 * (1 - 0.5 * 0.96^(t * decay))``."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = float(momentum_decay)
        self._use_master_weights = bool(multi_precision)

    def _init_state(self, p):
        pv = raw(p)
        dt = jnp.float32 if self._use_master_weights else pv.dtype
        return {"moment1": jnp.zeros(pv.shape, dt),
                "moment2": jnp.zeros(pv.shape, dt),
                "mu_product": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32),
                "step": jnp.zeros((), jnp.float32)}

    def _rule(self, p, g, st, lr):
        b1, b2, eps, psi = self._beta1, self._beta2, self._epsilon, self._psi
        t = st["step"] + 1.0
        mu_t = b1 * (1.0 - 0.5 * jnp.power(0.96, t * psi))
        mu_next = b1 * (1.0 - 0.5 * jnp.power(0.96, (t + 1.0) * psi))
        mu_prod = st["mu_product"] * mu_t
        b2p = st["beta2_pow"] * b2
        m1 = b1 * st["moment1"] + (1 - b1) * g
        m2 = b2 * st["moment2"] + (1 - b2) * jnp.square(g)
        mhat = mu_next * m1 / (1 - mu_prod * mu_next) + (1 - mu_t) * g / (1 - mu_prod)
        vhat = m2 / (1 - b2p)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p.astype(p.dtype), {
            "moment1": m1, "moment2": m2, "mu_product": mu_prod,
            "beta2_pow": b2p, "step": t}


class RAdam(Optimizer):
    """Rectified Adam (reference: ``python/paddle/optimizer/radam.py``):
    rectifies the adaptive term's variance when enough steps have accrued
    (rho_t > 4), otherwise falls back to un-adapted momentum SGD. The
    branch is a ``jnp.where`` on state scalars, so the rule stays one
    compiled program."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._use_master_weights = bool(multi_precision)

    def _init_state(self, p):
        pv = raw(p)
        dt = jnp.float32 if self._use_master_weights else pv.dtype
        return {"moment1": jnp.zeros(pv.shape, dt),
                "moment2": jnp.zeros(pv.shape, dt),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32),
                "step": jnp.zeros((), jnp.float32)}

    def _rule(self, p, g, st, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = st["step"] + 1.0
        b1p = st["beta1_pow"] * b1
        b2p = st["beta2_pow"] * b2
        m1 = b1 * st["moment1"] + (1 - b1) * g
        m2 = b2 * st["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m1 / (1 - b1p)
        rho_inf = 2.0 / (1.0 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * b2p / (1.0 - b2p)
        r_num = (rho_t - 4.0) * (rho_t - 2.0) * rho_inf
        r_den = (rho_inf - 4.0) * (rho_inf - 2.0) * rho_t
        rect = jnp.sqrt(jnp.maximum(r_num, 0.0) / jnp.maximum(r_den, eps))
        vhat = jnp.sqrt(m2 / (1 - b2p))
        adaptive = rect * mhat / (vhat + eps)
        new_p = p - lr * jnp.where(rho_t > 4.0, adaptive, mhat)
        return new_p.astype(p.dtype), {
            "moment1": m1, "moment2": m2, "beta1_pow": b1p,
            "beta2_pow": b2p, "step": t}


class LBFGS(Optimizer):
    """L-BFGS with backtracking (Armijo) line search.

    Reference: ``python/paddle/optimizer/lbfgs.py``. Unlike the first-order
    optimizers above, each `step(closure)` re-evaluates the loss: pass a
    closure that recomputes loss (and grads via backward), the standard
    paddle/torch LBFGS contract. The two-loop recursion runs on host over
    device arrays — dimensions involved are (history, params), not tokens,
    so there is nothing for the MXU here.
    """

    def __init__(self, learning_rate=1.0, max_iter=20, tolerance_grad=1e-7,
                 tolerance_change=1e-9, history_size=100, line_search_fn=None,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.max_iter = max_iter
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s, self._y = [], []
        self._prev_flat = None
        self._prev_grad = None

    def _flat(self, vals):
        import jax.numpy as jnp

        return jnp.concatenate([jnp.reshape(v, (-1,)) for v in vals])

    def _unflat(self, flat):
        import jax.numpy as jnp

        out, off = [], 0
        for p in self._parameter_list:
            n = int(np.prod(p.shape)) if p.shape else 1
            out.append(jnp.reshape(flat[off:off + n], p.shape))
            off += n
        return out

    def _direction(self, g):
        import jax.numpy as jnp

        q = g
        alphas = []
        for s, y in reversed(list(zip(self._s, self._y))):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            alphas.append((a, rho, s, y))
            q = q - a * y
        if self._s:
            s, y = self._s[-1], self._y[-1]
            q = q * (jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-10))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return -q

    def step(self, closure=None):
        import jax.numpy as jnp

        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the loss")
        loss = closure()
        flat = self._flat([raw(p) for p in self._parameter_list])
        grads = [
            raw(p.grad) if p.grad is not None else jnp.zeros(p.shape)
            for p in self._parameter_list
        ]
        g = self._flat(grads)
        if float(jnp.max(jnp.abs(g))) <= self.tol_grad:
            return loss
        if self._prev_flat is not None:
            s = flat - self._prev_flat
            y = g - self._prev_grad
            if float(jnp.vdot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
        d = self._direction(g)
        lr = self.get_lr()
        f0 = float(raw(loss))
        gtd = float(jnp.vdot(g, d))
        t = lr
        new_flat = flat
        for trial in range(10):  # backtracking Armijo
            new_flat = flat + t * d
            for p, v in zip(self._parameter_list, self._unflat(new_flat)):
                p._rebind(v)
            self.clear_grad()
            f1 = float(raw(closure()))
            if f1 <= f0 + 1e-4 * t * gtd:
                break
            if trial < 9:
                t *= 0.5
        # record the point the parameters are ACTUALLY at — a mismatched
        # _prev_flat would corrupt the next (s, y) curvature pair
        self._prev_flat = new_flat
        self._prev_grad = self._flat([
            raw(p.grad) if p.grad is not None else jnp.zeros(p.shape)
            for p in self._parameter_list
        ])
        return loss
