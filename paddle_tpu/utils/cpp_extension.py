"""paddle.utils.cpp_extension parity — build-and-load custom native code.

Reference: ``python/paddle/utils/cpp_extension/`` — compiles user C++ (with
paddle headers) into a custom-op module via setuptools.

TPU-native design: custom device kernels are Pallas's job, so the real
remaining use case is HOST-side native code. ``load`` compiles the given
C/C++ sources into a shared library with the toolchain in this image (g++)
and returns a ``ctypes.CDLL`` — the same mechanism the framework's own
C++ runtime uses (``paddle_tpu/runtime/native.py``). Wrap exported
functions with ``paddle.static.py_func`` / ``jax.pure_callback`` to call
them inside compiled programs.
"""
from __future__ import annotations

import ctypes
import os
import re
import subprocess
import tempfile


def load(name, sources, extra_cxx_cflags=None, extra_ldflags=None,
         build_directory=None, verbose=False, **kwargs):
    """Compile ``sources`` (C/C++ files) into ``lib<name>.so`` and return
    the loaded ``ctypes.CDLL``. Raises CalledProcessError with the full
    compiler output on failure."""
    if build_directory is None:
        # per-user, 0700: a world-shared fixed /tmp path would both break
        # on multi-user boxes and allow lib planting between build and load
        build_directory = os.path.join(
            tempfile.gettempdir(), f"paddle_tpu_cpp_ext_{os.getuid()}")
    build_dir = build_directory
    os.makedirs(build_dir, mode=0o700, exist_ok=True)
    src_list = [str(s) for s in (
        sources if isinstance(sources, (list, tuple)) else [sources])]
    cmd_tail = src_list + list(extra_cxx_cflags or []) + list(extra_ldflags or [])
    # version the artifact by source (+ locally-included header) mtimes AND
    # the full compile command: dlopen caches by PATH, so rebuilding into
    # the same .so would silently serve the old image — including one built
    # with different flags or edited #include'd headers
    import hashlib

    inc_dirs = [a[2:] for a in cmd_tail if a.startswith("-I") and len(a) > 2]
    deps = list(src_list)
    seen = set(deps)
    queue = list(src_list)
    while queue:
        path = queue.pop()
        try:
            with open(path, "r", errors="ignore") as fh:
                text = fh.read()
        except OSError:
            continue
        for m in re.finditer(r'^\s*#\s*include\s*([<"])([^">]+)[">]', text,
                             re.M):
            # quoted includes resolve includer-relative first, then through
            # any -I dirs from the flags; angle includes only through -I dirs
            # (system headers won't resolve there and are skipped — toolchain
            # headers don't need to stamp the artifact, project ones do)
            bases = inc_dirs if m.group(1) == "<" else (
                [os.path.dirname(os.path.abspath(path))] + inc_dirs)
            for base in bases:
                cand = os.path.normpath(os.path.join(base, m.group(2)))
                if os.path.exists(cand):
                    if cand not in seen:
                        seen.add(cand)
                        deps.append(cand)
                        queue.append(cand)
                    break

    def _mtime(d):
        # a dep deleted between discovery and stat must not crash load();
        # 0 still perturbs the stamp vs. the file existing
        try:
            return os.stat(d).st_mtime_ns
        except OSError:
            return 0

    stamp = hashlib.sha256(("\x00".join(
        cmd_tail + [f"{d}:{_mtime(d)}" for d in sorted(deps)]
    )).encode()).hexdigest()[:16]
    out = os.path.join(build_dir, f"lib{name}_{stamp}.so")
    if os.path.exists(out):
        # the stamp covers sources' ns-precision mtimes + the full compile
        # command, so an existing artifact is exactly what a rebuild would
        # produce (and it only appears at this path via the atomic rename
        # below — never partially written)
        return ctypes.CDLL(out)
    tmp = f"{out}.tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-o", tmp] + cmd_tail
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if verbose:
            print(" ".join(cmd))
            print(proc.stdout, proc.stderr)
        if proc.returncode != 0:
            raise subprocess.CalledProcessError(
                proc.returncode, cmd, proc.stdout, proc.stderr)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return ctypes.CDLL(out)


class CppExtension:
    """Recorded extension spec (setup()-style API surface). ``name``
    distinguishes extensions when several are built in one setup() call."""

    def __init__(self, sources, name=None, *args, **kwargs):
        self.sources = sources
        self.name = name
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):
    raise NotImplementedError(
        "CUDAExtension: no CUDA on this TPU build — write device kernels "
        "with Pallas (paddle_tpu.ops.pallas) and host code with CppExtension")


def setup(**kwargs):
    """Minimal setup(): compiles every CppExtension in ext_modules eagerly
    (the reference delegates to setuptools; here load() is the mechanism).
    Each extension gets its own library name — ext.name, or
    ``<setup name>_<i>`` — so multiple extensions never overwrite each
    other's .so (dlopen caches by path)."""
    mods = {}
    base = kwargs.get("name", "custom_ops")
    exts = list(kwargs.get("ext_modules", []))
    for i, ext in enumerate(exts):
        name = ext.name or (base if len(exts) == 1 else f"{base}_{i}")
        mods[name] = load(name, ext.sources, **ext.kwargs)
    return mods
