"""paddle.utils.download parity (offline build).

Reference: ``python/paddle/utils/download.py`` — ``get_weights_path_from_url``
downloads a weights archive into ``~/.cache/paddle/hapi/weights`` (with md5
verification and decompression) and returns the local path.

This environment has no network egress, so the download step is gated: a URL
whose file is ALREADY in the cache directory (placed there out-of-band)
resolves and verifies exactly as upstream; anything else raises with
instructions instead of silently hanging on a dead socket.
"""
from __future__ import annotations

import hashlib
import os

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle/hapi/weights")


def _md5check(path: str, md5sum: str = None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str, md5sum: str = None,
                      check_exist: bool = True):
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(root_dir, fname)
    if not check_exist:
        raise RuntimeError(
            "paddle.utils.download: check_exist=False forces a re-download, "
            "which this offline build cannot do; pass check_exist=True to "
            "use the cached copy")
    if os.path.isfile(path):
        if _md5check(path, md5sum):
            return path
        raise RuntimeError(
            f"paddle.utils.download: {path!r} exists but its md5 does not "
            f"match {md5sum!r} — the cached file is corrupt or stale; "
            "replace it (no network egress to re-download)")
    raise RuntimeError(
        f"paddle.utils.download: {fname!r} is not in the local cache "
        f"({root_dir}) and this build has no network egress. Place the file "
        "there manually to use it (md5 is verified when provided)."
    )


def get_weights_path_from_url(url: str, md5sum: str = None):
    """Resolve a weights URL against the local cache (offline contract)."""
    os.makedirs(WEIGHTS_HOME, exist_ok=True)
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
