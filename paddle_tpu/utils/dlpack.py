"""paddle.utils.dlpack parity (``python/paddle/utils/dlpack.py``):
zero-copy-where-possible tensor interchange via the DLPack protocol.

``to_dlpack`` first lands the array on host (DLPack has no TPU device
type; the on-device buffer raises UNIMPLEMENTED for external references
under PJRT) and hands out a capsule any consumer (torch, numpy>=1.23,
cupy) accepts. ``from_dlpack`` ingests either a raw capsule (wrapped in a
CPU-device adapter — jax 0.9 only accepts ``__dlpack__``-bearing objects)
or any object implementing the protocol (e.g. torch tensors).
"""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def _to_host(v):
    import jax

    dev = getattr(v, "device", None)
    plat = getattr(dev, "platform", None)
    if plat == "cpu":
        return v
    return jax.device_put(v, jax.devices("cpu")[0])


def to_dlpack(x):
    from ..framework.op import raw

    v = _to_host(raw(x))
    v.block_until_ready()
    return v.__dlpack__()


class _CapsuleAdapter:
    """Expose a raw DLPack capsule through the array-protocol form modern
    consumers require. Capsules we produce are host-resident (see
    to_dlpack), so the device is kDLCPU; the capsule is consumable once."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # (kDLCPU, device 0)


def from_dlpack(capsule_or_tensor):
    import jax.numpy as jnp

    from ..framework.core import Tensor

    obj = capsule_or_tensor
    if not hasattr(obj, "__dlpack__"):  # raw PyCapsule
        obj = _CapsuleAdapter(obj)
    return Tensor(jnp.from_dlpack(obj))
