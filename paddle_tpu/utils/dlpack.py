"""paddle.utils.dlpack parity (``python/paddle/utils/dlpack.py``):
zero-copy tensor interchange via the DLPack protocol. jax.Arrays implement
``__dlpack__`` natively, so ``to_dlpack`` hands out a capsule any consumer
(torch, numpy>=1.23, cupy) accepts, and ``from_dlpack`` ingests capsules or
any ``__dlpack__``-bearing object (e.g. torch tensors)."""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    from ..framework.op import raw

    return raw(x).__dlpack__()


def from_dlpack(capsule_or_tensor):
    import jax.numpy as jnp

    from ..framework.core import Tensor

    return Tensor(jnp.from_dlpack(capsule_or_tensor))
