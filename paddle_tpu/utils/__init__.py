"""paddle.utils parity: import helpers, checks, unique names, deprecation.

Reference: ``python/paddle/utils/`` (download/lazy-import/env checks).
Network-dependent pieces (download, hub) are gated for this offline
environment and raise with guidance.
"""
from __future__ import annotations

import importlib
import warnings

_name_counters = {}


def try_import(module_name: str, err_msg: str = None):
    """Import a module, raising a readable error when absent
    (reference: paddle.utils.try_import)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed "
            f"(offline image: only baked-in packages are available)"
        ) from e


def run_check():
    """paddle.utils.run_check parity: verify the backend computes."""
    import numpy as np

    import paddle_tpu as paddle

    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    out = (a @ a).numpy()
    assert out[0, 0] == 2.0
    import jax

    print(
        f"paddle_tpu is installed successfully! backend="
        f"{jax.default_backend()}, devices={len(jax.devices())}"
    )


def unique_name(prefix: str = "var") -> str:
    """Monotonic unique names (reference: paddle.utils.unique_name.generate)."""
    n = _name_counters.get(prefix, 0)
    _name_counters[prefix] = n + 1
    return f"{prefix}_{n}"


class _UniqueNameNS:
    generate = staticmethod(unique_name)

    @staticmethod
    def guard(new_generator=None):
        import contextlib

        return contextlib.nullcontext()


unique_name_ns = _UniqueNameNS()


def deprecated(update_to: str = "", since: str = "", reason: str = "", level=1):
    """Decorator emitting a DeprecationWarning on first call."""

    def deco(fn):
        warned = []

        def wrapper(*a, **k):
            if not warned:
                warned.append(1)
                warnings.warn(
                    f"{fn.__name__} is deprecated since {since}: {reason}"
                    + (f"; use {update_to}" if update_to else ""),
                    DeprecationWarning,
                )
            return fn(*a, **k)

        wrapper.__name__ = fn.__name__
        return wrapper

    return deco


def require_version(min_version: str, max_version: str = None):
    """Check the installed framework version against bounds
    (paddle.utils.require_version)."""
    from .. import __version__

    def key(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    if key(__version__) < key(min_version):
        raise Exception(
            f"version {__version__} < required minimum {min_version}")
    if max_version is not None and key(__version__) > key(max_version):
        raise Exception(
            f"version {__version__} > allowed maximum {max_version}")
    return True

from . import dlpack  # noqa: E402,F401
from . import download  # noqa: E402,F401  (module, as upstream)
from . import cpp_extension  # noqa: E402,F401
