"""Random number state management.

Reference capability: Paddle's global/generator seeds (``paddle.seed``) and
Fleet's ``RNGStatesTracker`` for tensor-parallel dropout
(``python/paddle/distributed/fleet/layers/mpu/random.py`` — SURVEY.md §2.3 "TP").

TPU-native design: JAX's splittable counter-based PRNG. A global ``Generator``
holds a base key + a monotonically increasing offset; each random op folds the
offset in. Inside a captured/compiled program (``paddle_tpu.jit``), the step
machinery seeds a *trace-scoped* key so every compiled call sees fresh
randomness via an explicit key argument (stateful RNG inside an XLA program
would bake constants into the executable). Named-axis generators mirror the
reference's RNGStatesTracker: the "local" generator additionally folds in the
process/mesh coordinate so tensor-parallel dropout masks are decorrelated.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax


class Generator:
    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = jax.random.key(seed)
        self._offset = 0
        self._lock = threading.Lock()

    def manual_seed(self, seed: int):
        self._seed = seed
        self._key = jax.random.key(seed)
        self._offset = 0
        return self

    def get_state(self):
        return (self._seed, self._offset)

    def set_state(self, state):
        self._seed, self._offset = state
        self._key = jax.random.key(self._seed)

    def next_key(self):
        with self._lock:
            off = self._offset
            self._offset += 1
        return jax.random.fold_in(self._key, off)

    @property
    def initial_seed(self):
        return self._seed


_default_generator = Generator(0)

# Trace-scoped key: when paddle_tpu.jit traces a function, it installs a key
# here (a tracer); random ops consume splits of it instead of the global state.
_trace_state = threading.local()


def default_generator() -> Generator:
    return _default_generator


def seed(value: int) -> Generator:
    """Set the global random seed (paddle.seed parity)."""
    return _default_generator.manual_seed(int(value))


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


@contextlib.contextmanager
def trace_key_scope(key):
    """Install a trace-scoped RNG key (used by the jit machinery)."""
    prev = getattr(_trace_state, "key", None)
    prev_n = getattr(_trace_state, "n", 0)
    _trace_state.key = key
    _trace_state.n = 0
    try:
        yield
    finally:
        _trace_state.key = prev
        _trace_state.n = prev_n


def in_trace_scope() -> bool:
    return getattr(_trace_state, "key", None) is not None


def next_key(generator: Optional[Generator] = None):
    """Produce a fresh PRNG key for one random op."""
    tk = getattr(_trace_state, "key", None)
    if tk is not None:
        n = _trace_state.n
        _trace_state.n = n + 1
        return jax.random.fold_in(tk, n)
    return (generator or _default_generator).next_key()
