"""Random number state management.

Reference capability: Paddle's global/generator seeds (``paddle.seed``) and
Fleet's ``RNGStatesTracker`` for tensor-parallel dropout
(``python/paddle/distributed/fleet/layers/mpu/random.py`` — SURVEY.md §2.3 "TP").

TPU-native design: JAX's splittable counter-based PRNG. A global ``Generator``
holds a base key + a monotonically increasing offset; each random op folds the
offset in. Inside a captured/compiled program (``paddle_tpu.jit``), the step
machinery seeds a *trace-scoped* key so every compiled call sees fresh
randomness via an explicit key argument (stateful RNG inside an XLA program
would bake constants into the executable). Named-axis generators mirror the
reference's RNGStatesTracker: the "local" generator additionally folds in the
process/mesh coordinate so tensor-parallel dropout masks are decorrelated.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

import jax


def _configure_default_prng():
    """TPU-idiomatic PRNG selection (measured on v5e, round 3).

    JAX's default threefry2x32 PRNG is computed in plain vector ops and is
    expensive on TPU: for the ERNIE-base headline bench, dropout-mask
    generation alone cost ~36ms of a 234ms train step (measured in-session
    on a v5e chip, 2026-07-31; the committed bench artifact refreshes on
    the next successful real-chip run). The ``rbg`` impl rides the
    hardware RNG instruction and took the same step to 198ms
    (+18% throughput) with the same statistical contract Paddle offers
    (deterministic per seed; streams are not bit-stable across XLA
    versions, which the reference never guaranteed across cuDNN versions
    either).

    Selection, most-specific wins:

    1. ``PADDLE_TPU_PRNG_IMPL`` env: applied verbatim (``threefry`` is
       actively set, so the opt-out wins even if something else flipped
       the jax default earlier).
    2. Deference: if the application configured the PRNG itself — jax's
       native ``JAX_DEFAULT_PRNG_IMPL`` env, or ``jax.config`` no longer
       at its threefry default when paddle imports — leave it alone.
    3. Auto: rbg, but only when a TPU is *plausibly present* (libtpu
       importable, a TPU/axon env marker, or JAX_PLATFORMS's primary
       platform says tpu/axon) AND the primary platform is not cpu.
       The 8-virtual-device CPU test mesh pins ``JAX_PLATFORMS=cpu`` and
       a CPU-only dev box has no TPU markers — both keep threefry, so
       recorded CPU trajectories stay stable. ``JAX_PLATFORMS="tpu,cpu"``
       (cpu as fallback only) still selects rbg.

    No jax backend is initialized here — the decision reads only env vars
    and the config default, so importing paddle stays cheap.

    Known limit: an in-process ``jax.config.update("jax_default_prng_impl",
    "threefry2x32")`` before importing paddle is indistinguishable from the
    untouched default (jax does not expose "was it set"), so it does not
    defer; pin ``PADDLE_TPU_PRNG_IMPL=threefry`` (or jax's own
    ``JAX_DEFAULT_PRNG_IMPL``) for a guaranteed opt-out.
    """
    explicit = os.environ.get("PADDLE_TPU_PRNG_IMPL", "").strip().lower()
    if explicit in ("threefry", "default"):
        explicit = "threefry2x32"
    impl = explicit
    if not impl:
        if os.environ.get("JAX_DEFAULT_PRNG_IMPL"):
            return  # app configured jax's own env knob: defer
        try:
            if jax.config.jax_default_prng_impl != "threefry2x32":
                return  # app already changed the default in-process: defer
        except AttributeError:
            return
        primary = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower()
        if primary == "cpu" or not _tpu_plausible(primary):
            return
        impl = "rbg"
    try:
        jax.config.update("jax_default_prng_impl", impl)
    except Exception as e:
        if explicit:
            import warnings

            warnings.warn(
                f"PADDLE_TPU_PRNG_IMPL={explicit!r} was rejected by JAX "
                f"({e}); keeping the default PRNG", RuntimeWarning)
        # implicit auto-selection: very old jax / unknown impl — keep default


def _tpu_plausible(primary_platform: str) -> bool:
    """Cheap TPU-presence heuristics that never initialize a backend."""
    if primary_platform in ("tpu", "axon"):
        return True
    for var in ("PALLAS_AXON_POOL_IPS", "TPU_NAME", "TPU_WORKER_ID",
                "TPU_SKIP_MDS_QUERY", "CLOUD_TPU_TASK_ID"):
        if os.environ.get(var):
            return True
    try:
        import importlib.util

        if importlib.util.find_spec("libtpu") is None:
            return False
    except (ImportError, ValueError):
        return False
    # an installed libtpu wheel alone is not presence (TPU docker image on
    # a CPU VM): require a local accelerator device node to go with it
    import glob

    return bool(glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*"))


_configure_default_prng()


class Generator:
    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = jax.random.key(seed)
        self._offset = 0
        self._lock = threading.Lock()

    def manual_seed(self, seed: int):
        self._seed = seed
        self._key = jax.random.key(seed)
        self._offset = 0
        return self

    def get_state(self):
        return (self._seed, self._offset)

    def set_state(self, state):
        self._seed, self._offset = state
        self._key = jax.random.key(self._seed)

    def next_key(self):
        with self._lock:
            off = self._offset
            self._offset += 1
        return jax.random.fold_in(self._key, off)

    @property
    def initial_seed(self):
        return self._seed


# LAZY: creating a Generator touches the XLA backend (jax.random.key), and
# backend init must not happen at import time — multi-host workers need
# jax.distributed.initialize() to run first (distributed/env.py).
_default_generator = None
_default_lock = threading.Lock()


def _default() -> Generator:
    global _default_generator
    if _default_generator is None:
        with _default_lock:
            if _default_generator is None:
                _default_generator = Generator(0)
    return _default_generator


# Trace-scoped key: when paddle_tpu.jit traces a function, it installs a key
# here (a tracer); random ops consume splits of it instead of the global state.
_trace_state = threading.local()


def default_generator() -> Generator:
    return _default()


def seed(value: int) -> Generator:
    """Set the global random seed (paddle.seed parity)."""
    return _default().manual_seed(int(value))


def get_rng_state():
    return _default().get_state()


def set_rng_state(state):
    _default().set_state(state)


@contextlib.contextmanager
def trace_key_scope(key):
    """Install a trace-scoped RNG key (used by the jit machinery)."""
    prev = getattr(_trace_state, "key", None)
    prev_n = getattr(_trace_state, "n", 0)
    _trace_state.key = key
    _trace_state.n = 0
    try:
        yield
    finally:
        _trace_state.key = prev
        _trace_state.n = prev_n


def in_trace_scope() -> bool:
    return getattr(_trace_state, "key", None) is not None


def next_key(generator: Optional[Generator] = None):
    """Produce a fresh PRNG key for one random op."""
    tk = getattr(_trace_state, "key", None)
    if tk is not None:
        n = _trace_state.n
        _trace_state.n = n + 1
        return jax.random.fold_in(tk, n)
    return (generator or _default()).next_key()
