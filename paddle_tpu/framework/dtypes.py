"""Dtype system for paddle_tpu.

Reference capability: PaddlePaddle's ``phi::DataType`` / ``paddle.dtype``
(upstream ``paddle/phi/common/data_type.h``; see SURVEY.md §2.1 "PHI core").
TPU-native design: dtypes ARE jax/numpy dtypes; we expose paddle-style names
and conversion helpers. bfloat16 is the first-class reduced precision type on
TPU (MXU-native), float16 is supported but discouraged.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (numpy dtype instances; bfloat16 via ml_dtypes which
# jax re-exports as jnp.bfloat16).
bool_ = jnp.dtype("bool")
uint8 = jnp.dtype("uint8")
int8 = jnp.dtype("int8")
int16 = jnp.dtype("int16")
int32 = jnp.dtype("int32")
int64 = jnp.dtype("int64")
float16 = jnp.dtype("float16")
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype("float32")
float64 = jnp.dtype("float64")
complex64 = jnp.dtype("complex64")
complex128 = jnp.dtype("complex128")

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "fp16": float16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

FLOAT_DTYPES = (float16, bfloat16, float32, float64)
INT_DTYPES = (uint8, int8, int16, int32, int64)


def convert_dtype(dtype):
    """Normalize a user-facing dtype spec (str | np.dtype | jnp type) to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unsupported dtype string: {dtype!r}")
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    d = jnp.dtype(dtype)
    if d == bfloat16:
        return "bfloat16"
    return d.name


def is_floating_point(dtype) -> bool:
    d = jnp.dtype(dtype)
    return jnp.issubdtype(d, np.floating)  # covers bfloat16 via ml_dtypes


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), np.complexfloating)


def default_float_dtype():
    from . import flags

    return convert_dtype(flags.get_flags("FLAGS_default_float_dtype"))


# ---- default floating dtype (paddle.get/set_default_dtype) ----------------
_default_float = ["float32"]


def get_default_dtype() -> str:
    return _default_float[0]


def set_default_dtype(d) -> None:
    dt = convert_dtype(d)
    name = str(np.dtype(dt)) if not isinstance(d, str) else d
    if name not in ("float16", "bfloat16", "float32", "float64"):
        raise ValueError(f"default dtype must be a float type, got {name}")
    _default_float[0] = name
