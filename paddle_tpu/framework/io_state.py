"""paddle.save / paddle.load parity.

Reference: ``python/paddle/framework/io.py`` — pickled state dicts of
numpy-converted tensors (SURVEY.md §5 "Checkpoint/resume"). The distributed,
sharded, re-shardable checkpoint path (Orbax-style) lives in
``paddle_tpu.distributed.checkpoint``; this is the single-host format.
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from .core import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "value": np.asarray(obj._value), "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saved(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["value"]
            t = Tensor(obj["value"], stop_gradient=obj.get("stop_gradient", True), name=obj.get("name"))
            return t
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saved(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saved(obj, return_numpy=return_numpy)
