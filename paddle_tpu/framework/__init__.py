"""Framework core: Tensor, autograd tape, dtypes, flags, rng, op registry."""
from . import dtypes, flags, rng
from .core import (
    CPUPlace,
    CUDAPlace,
    Place,
    Tensor,
    TPUPlace,
    XPUPlace,
    enable_grad,
    is_grad_enabled,
    is_tensor,
    no_grad,
    run_backward,
    set_grad_enabled,
)
from .op import OP_REGISTRY, defop, raw

__all__ = [
    "Tensor",
    "Place",
    "CPUPlace",
    "TPUPlace",
    "CUDAPlace",
    "XPUPlace",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "is_tensor",
    "run_backward",
    "defop",
    "raw",
    "OP_REGISTRY",
    "dtypes",
    "flags",
    "rng",
]
