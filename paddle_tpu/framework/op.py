"""Op definition decorator — the single dispatch gateway for all tensor ops.

Reference capability: PaddlePaddle's YAML op registry + codegen
(``paddle/phi/api/yaml/ops.yaml`` → generated C++ API + eager autograd nodes;
SURVEY.md §2.1 "PHI API + codegen"). The reference generates, per op: a Python
binding, an AMP cast hook, a GradNode recorder, and a kernel dispatch.

TPU-native design: one Python decorator provides all four — the "kernel" is a
pure jax function (XLA does the per-backend lowering the reference hand-writes
per device), the GradNode is a ``jax.vjp`` pullback, AMP casting consults the
active ``paddle_tpu.amp.auto_cast`` policy, and under a JAX trace the wrapper
degrades to a plain function call so one op library serves both the eager and
the captured/compiled execution modes.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from . import dtypes as _dtypes
from .core import Tensor, TapeNode, is_grad_enabled, is_tracer_value

OP_REGISTRY: Dict[str, Callable] = {}

# Static-graph capture (reference: op recording into ProgramDesc under
# enable_static — SURVEY.md §2.1 "Legacy framework"). When a
# paddle_tpu.static.Program build is active (program_guard), every defop
# call also appends a replayable record to it; Executor.run later replays
# the list as ONE jit-compiled program with feeds substituted. `None` when
# no capture is active — a single attribute check on the eager hot path.
_capture_program = None


# Post-op observer hook (amp.debugging operator stats / tensor checker).
# None on the hot path — one attribute test per eager op call.
_op_observer = None


def set_op_observer(observer):
    """Install a callable (op_name, out_value_leaves) -> None run after
    every eager defop dispatch; None uninstalls. Serves
    paddle.amp.debugging's operator-stats and NaN/Inf-checker hooks."""
    global _op_observer
    _op_observer = observer


def set_capture_program(prog):
    global _capture_program
    prev = _capture_program
    _capture_program = prog
    return prev

# AMP op lists (mirrors the reference's white/black lists in
# ``python/paddle/amp/amp_lists.py``): "white" ops run in the low-precision
# dtype (MXU-bound: matmul/conv), "black" ops are kept in float32 for
# numerical safety.
AMP_WHITE = set()
AMP_BLACK = set()

# Active amp state is owned by paddle_tpu.amp; it mutates this holder to avoid
# an import cycle. Fields: enable(bool), dtype(jnp dtype), level('O1'|'O2').
class _AmpState:
    __slots__ = ("enable", "dtype", "level")

    def __init__(self):
        self.enable = False
        self.dtype = _dtypes.bfloat16
        self.level = "O1"


amp_state = _AmpState()


def _amp_cast(opname, vals):
    if not amp_state.enable:
        return vals
    in_white = opname in AMP_WHITE
    in_black = opname in AMP_BLACK
    if amp_state.level == "O2":
        target = _dtypes.float32 if in_black else amp_state.dtype
    else:
        if in_white:
            target = amp_state.dtype
        elif in_black:
            target = _dtypes.float32
        else:
            return vals
    out = []
    for v in vals:
        if v is not None and _dtypes.is_floating_point(v.dtype) and v.dtype != target:
            v = v.astype(target)
        out.append(v)
    return out


def _is_tensor_leaf(x):
    return isinstance(x, Tensor)


def defop(fn=None, *, name: Optional[str] = None, amp: Optional[str] = None):
    """Register ``fn`` (a pure jax function) as a framework op.

    The wrapper accepts Tensors (or anything jnp accepts) wherever ``fn``
    expects arrays, including inside lists/tuples/dicts, and returns Tensors
    in the same structure ``fn`` returns arrays.
    """

    def deco(f):
        opname = name or f.__name__
        if amp == "white":
            AMP_WHITE.add(opname)
        elif amp == "black":
            AMP_BLACK.add(opname)

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            leaves, treedef = jax.tree_util.tree_flatten(
                (args, kwargs), is_leaf=_is_tensor_leaf
            )
            t_idx = []  # differentiable (float/complex) tensor leaf positions
            t_vals = []
            diff_tensors = []
            any_tracer = False
            need_grad = False
            grad_on = is_grad_enabled()
            vals = list(leaves)
            for i, leaf in enumerate(leaves):
                if isinstance(leaf, Tensor):
                    v = leaf._value
                    vals[i] = v
                    if is_tracer_value(v):
                        any_tracer = True
                    if _dtypes.is_floating_point(v.dtype) or _dtypes.is_complex(
                        v.dtype
                    ):
                        t_idx.append(i)
                        t_vals.append(v)
                        diff_tensors.append(leaf)
                        if grad_on and not leaf.stop_gradient:
                            need_grad = True

            if t_vals:
                cast = _amp_cast(opname, t_vals)
                if cast is not t_vals:
                    for i, v in zip(t_idx, cast):
                        vals[i] = v
                    t_vals = cast

            record = need_grad and not any_tracer

            if not record:
                a, k = jax.tree_util.tree_unflatten(treedef, vals)
                out = f(*a, **k)
                res = _wrap_outputs(out, node=None, any_tracer=any_tracer)
                if _capture_program is not None and not any_tracer:
                    _record_capture(
                        _capture_program, f, treedef, leaves, vals, res
                    )
                if _op_observer is not None and not any_tracer:
                    _op_observer(opname, jax.tree_util.tree_leaves(out))
                return res

            const_vals = list(vals)

            def pure(*tv):
                vs = list(const_vals)
                for i, v in zip(t_idx, tv):
                    vs[i] = v
                a, k = jax.tree_util.tree_unflatten(treedef, vs)
                return f(*a, **k)

            out, vjp_fn = jax.vjp(pure, *t_vals)
            out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
            metas = [(tuple(o.shape), o.dtype) for o in out_leaves]
            node = TapeNode(opname, vjp_fn, tuple(diff_tensors), metas, out_treedef)
            res = _wrap_outputs(out, node=node, any_tracer=False)
            if _capture_program is not None:
                _record_capture(_capture_program, f, treedef, leaves, vals, res)
            if _op_observer is not None:
                _op_observer(opname, out_leaves)
            return res

        wrapper.op_name = opname
        wrapper.raw_fn = f
        OP_REGISTRY[opname] = wrapper
        return wrapper

    if fn is not None:
        return deco(fn)
    return deco


def _wrap_outputs(out, node, any_tracer):
    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
    wrapped = []
    uids = []
    for o in out_leaves:
        t = Tensor(o, stop_gradient=(node is None))
        if node is not None:
            if not (_dtypes.is_floating_point(o.dtype) or _dtypes.is_complex(o.dtype)):
                t.stop_gradient = True
            t._node = node
        wrapped.append(t)
        uids.append(t._uid)
    if node is not None:
        node.out_uids = tuple(uids)
    res = jax.tree_util.tree_unflatten(out_treedef, wrapped)
    return res


def _record_capture(prog, f, treedef, leaves, vals, res):
    """Append one replayable op record to the active static Program.

    Tensor inputs are recorded by uid (resolved at replay time to the fed
    value, an earlier op's output, or the tensor's CURRENT live value — so
    parameters update without re-capturing); everything else is a constant.
    The dtype each tensor leaf was actually fed to the kernel with (i.e.
    AFTER the AMP cast in the wrapper) is recorded so replay reproduces
    auto_cast behavior exactly.
    """
    import weakref

    descs = []
    for leaf, v in zip(leaves, vals):
        if isinstance(leaf, Tensor):
            descs.append(("t", leaf._uid, str(v.dtype)))
            prog._tensor_refs[leaf._uid] = weakref.ref(leaf)
        else:
            descs.append(("c", leaf))
    out_leaves = jax.tree_util.tree_leaves(res, is_leaf=_is_tensor_leaf)
    out_uids = []
    for o in out_leaves:
        if isinstance(o, Tensor):
            out_uids.append(o._uid)
            prog._tensor_refs[o._uid] = weakref.ref(o)  # name-based fetch
        else:
            out_uids.append(None)
    prog._ops.append((f, treedef, tuple(descs), tuple(out_uids)))


def raw(x):
    """Unwrap a Tensor (or pass through arrays/scalars) to a jax value."""
    return x._value if isinstance(x, Tensor) else x
