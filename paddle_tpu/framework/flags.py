"""Runtime flag system.

Reference capability: PaddlePaddle's gflags-style runtime flags
(``paddle/phi/core/flags.cc``; ``paddle.set_flags``/``paddle.get_flags`` —
SURVEY.md §5 "Config/flag system"). TPU-native design: a plain in-process
registry; XLA knobs pass through to the XLA_FLAGS env var.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Union

_FLAG_DEFAULTS: Dict[str, Any] = {
    # numeric / execution behavior
    "FLAGS_default_float_dtype": "float32",
    "FLAGS_cudnn_deterministic": False,  # accepted for API parity; XLA is deterministic
    "FLAGS_deterministic": True,
    # eager engine
    "FLAGS_retain_grad_for_all_tensor": False,
    # memory (informational on TPU; PJRT owns the allocator)
    "FLAGS_allocator_strategy": "pjrt",
    "FLAGS_fraction_of_gpu_memory_to_use": 1.0,
    # logging
    "FLAGS_log_level": int(os.environ.get("PADDLE_TPU_LOG_LEVEL", "0")),
    # jit / tracing
    "FLAGS_jit_cache_size": 128,
    "FLAGS_use_donated_buffers": True,
    # amp
    "FLAGS_amp_dtype": "bfloat16",
    # benchmarking
    "FLAGS_benchmark": False,
}

_flags: Dict[str, Any] = dict(_FLAG_DEFAULTS)


def set_flags(flags: Dict[str, Any]) -> None:
    for k, v in flags.items():
        if k not in _FLAG_DEFAULTS:
            raise ValueError(f"Unknown flag {k!r}. Known flags: {sorted(_FLAG_DEFAULTS)}")
        _flags[k] = v


def get_flags(flags: Union[str, Iterable[str]]):
    if isinstance(flags, str):
        return _flags[flags]
    return {k: _flags[k] for k in flags}
