"""Tensor and the eager autograd tape.

Reference capability reproduced here (SURVEY.md §1 L3, §3.1/§3.2):
  * ``paddle.Tensor`` — imperative tensor with ``stop_gradient`` semantics
    (upstream: ``paddle/fluid/eager/`` EagerVariable + pybind eager tensor).
  * DyGraph autograd — grad-node graph recorded during forward, walked by
    ``Tensor.backward()`` (upstream: ``paddle/fluid/eager/backward.cc``,
    generated ``*GradNode``s).

TPU-native design: instead of hand-written per-op C++ GradNodes, forward ops
run under ``jax.vjp`` — XLA traces the forward once and hands back a pullback
closure; the "grad node" IS that closure. Residuals live on device as jax
arrays. Inside a functional transform (``paddle_tpu.jit``/``jax.grad``) the
tape stays silent (values are tracers) and differentiation is handled by JAX —
one op library, two execution modes, mirroring the reference's
"single PHI kernel library, two frontends" shape.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as _dtypes

try:  # Tracer detection — used to auto-disable the tape under jax transforms
    _Tracer = jax.core.Tracer
except AttributeError:  # pragma: no cover - older/newer jax layouts
    from jax._src.core import Tracer as _Tracer

_float0 = jax.dtypes.float0

# --------------------------------------------------------------------------
# Grad-mode state
# --------------------------------------------------------------------------
_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def _set_grad_enabled(v: bool):
    _state.grad_enabled = v


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad parity: context manager + decorator."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._prev)
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._prev)
        return False


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    prev = is_grad_enabled()
    _set_grad_enabled(bool(mode))
    try:
        yield
    finally:
        _set_grad_enabled(prev)


# --------------------------------------------------------------------------
# Places (device handles) — API parity with paddle.CPUPlace/CUDAPlace/...
# The reference dispatches kernels by Place; here a Place is a jax.Device tag.
# --------------------------------------------------------------------------
class Place:
    def __init__(self, kind: str, device_id: int = 0):
        self.kind = kind
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.kind}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.kind, self.device_id))


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(device_id: int = 0):
    return Place("tpu", device_id)


def CUDAPlace(device_id: int = 0):  # accepted for script compatibility
    return Place("tpu", device_id)


def XPUPlace(device_id: int = 0):
    return Place("tpu", device_id)


# --------------------------------------------------------------------------
# Tape
# --------------------------------------------------------------------------
class TraceHostSyncError(RuntimeError):
    """Raised when a host sync point (`.numpy()`, `float()`, `if tensor:`)
    is hit on a traced value inside a captured program. `jit.to_static`
    catches this to fall back to eager execution (the dy2static guard
    story — SURVEY.md §7 hard-part #1)."""


class TapeNode:
    """One recorded op: pullback closure + graph edges.

    ``inputs`` are the differentiable input Tensors (strong refs keep the
    upstream graph alive until backward releases it); ``out_metas`` lets
    backward synthesize zero cotangents for unused outputs.
    """

    __slots__ = ("op_name", "vjp_fn", "inputs", "out_metas", "out_treedef", "out_uids")

    def __init__(self, op_name, vjp_fn, inputs, out_metas, out_treedef):
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.inputs = inputs
        self.out_metas = out_metas  # list of (shape, dtype)
        self.out_treedef = out_treedef
        self.out_uids = ()  # filled in by defop once output Tensors exist


def _zero_cotangent(meta):
    shape, dtype = meta
    if _dtypes.is_floating_point(dtype) or _dtypes.is_complex(dtype):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, _float0)


def run_backward(
    tensors: Sequence["Tensor"],
    grad_tensors: Optional[Sequence[Optional["Tensor"]]] = None,
    retain_graph: bool = False,
):
    """Reverse-walk the tape from ``tensors`` (paddle.autograd.backward parity).

    Reference analogue: ``egr::Backward`` ready-queue over GradNodes
    (SURVEY.md §3.2). Here: topological sort over TapeNodes, cotangent
    accumulation per tensor, one ``vjp_fn`` call per node.
    """
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors must match tensors in length")

    # cotangent accumulator keyed by tensor uid; uid->tensor map keeps refs
    cts = {}
    id2t = {}

    def _acc(t: "Tensor", ct):
        if isinstance(ct, np.ndarray) and ct.dtype == _float0:
            return
        k = t._uid
        id2t[k] = t
        if k in cts:
            cts[k] = cts[k] + ct
        else:
            cts[k] = ct

    roots = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                "Trying to backward through a tensor with stop_gradient=True"
            )
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward roots"
                )
            g_val = jnp.ones_like(t._value)
        else:
            g_val = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        _acc(t, g_val)
        if t._node is not None:
            roots.append(t._node)

    # Topological order over nodes (iterative postorder DFS).
    topo: List[TapeNode] = []
    seen = set()
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            topo.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for it in node.inputs:
            if it._node is not None and id(it._node) not in seen:
                stack.append((it._node, False))

    from . import flags as _flags

    retain_all = _flags.get_flags("FLAGS_retain_grad_for_all_tensor")

    for node in reversed(topo):
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to run backward a second time through a released graph; "
                "pass retain_graph=True to backward()."
            )
        # collect output cotangents; skip node entirely if none of its outputs
        # received a cotangent (dead branch)
        out_cts = []
        any_ct = False
        for meta, out_id in zip(node.out_metas, node.out_uids):
            ct = cts.pop(out_id, None)
            if ct is None:
                out_cts.append(_zero_cotangent(meta))
            else:
                any_ct = True
                out_cts.append(ct)
                id2t.pop(out_id, None)
        if not any_ct:
            continue
        ct_tree = jax.tree_util.tree_unflatten(node.out_treedef, out_cts)
        in_cts = node.vjp_fn(ct_tree)
        if not retain_graph:
            node.vjp_fn = None
        for t, g in zip(node.inputs, in_cts):
            if t.stop_gradient:
                continue
            if isinstance(g, np.ndarray) and g.dtype == _float0:
                continue
            if t._node is None or t._retain_grads or retain_all:
                t._accumulate_grad(g)
            if t._node is not None:
                _acc(t, g)

    # leaves among the roots themselves (e.g. x.backward() where x is a leaf)
    for k, ct in list(cts.items()):
        t = id2t.get(k)
        if t is not None and t._node is None and not t.stop_gradient:
            t._accumulate_grad(ct)


# --------------------------------------------------------------------------
# Tensor
# --------------------------------------------------------------------------
import itertools as _itertools

_uid_counter = _itertools.count()


class Tensor:
    """Imperative tensor backed by a jax.Array (or a JAX tracer under jit).

    paddle.Tensor parity surface; most math methods are patched on by
    ``paddle_tpu.tensor`` after the op library is defined (mirroring the
    reference, where Python monkey-patches methods onto the pybind tensor —
    ``python/paddle/base/dygraph/tensor_patch_methods.py``).
    """

    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "name",
        "persistable",
        "trainable",
        "_node",
        "_uid",
        "_retain_grads",
        "_hooks",
        # distributed metadata (auto_parallel / fleet placement)
        "dist_spec",
        "process_mesh",
        "placements",
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jnp.ndarray, jax.Array)) and not isinstance(
            value, _Tracer
        ):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._node = None
        self._uid = next(_uid_counter)
        self._retain_grads = False
        self._hooks = None

    # -- basic properties ------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._value.shape)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    ndimension = ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self) -> Place:
        try:
            dev = list(self._value.devices())[0]
            kind = "cpu" if dev.platform == "cpu" else "tpu"
            return Place(kind, dev.id)
        except Exception:
            return Place("tpu", 0)

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g if (g is None or isinstance(g, Tensor)) else Tensor(g)

    def _accumulate_grad(self, g):
        if self._hooks:
            for h in self._hooks:
                out = h(Tensor(g))
                if out is not None:
                    g = out._value if isinstance(out, Tensor) else out
        if self._grad is None:
            self._grad = Tensor(jnp.asarray(g))
        else:
            self._grad = Tensor(self._grad._value + g)

    def register_hook(self, hook):
        """Hook on the gradient flowing into this tensor (paddle parity)."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Removable:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Removable(self._hooks, hook)

    def retain_grads(self):
        self._retain_grads = True

    # -- autograd --------------------------------------------------------
    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph=False):
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self) -> "Tensor":
        self._node = None
        self.stop_gradient = True
        return self

    # -- materialization -------------------------------------------------
    def numpy(self) -> np.ndarray:
        if isinstance(self._value, _Tracer):
            raise TraceHostSyncError(
                "Tensor.numpy() is not allowed inside a captured (jit) program; "
                "this is a host sync point. paddle_tpu.jit.to_static catches "
                "this and falls back to eager execution with a warning; under "
                "raw jax.jit, move the sync outside the traced region or use "
                "paddle_tpu.static.nn.cond/while_loop for data-dependent "
                "control flow."
            )
        return np.asarray(self._value)

    def item(self, *idx):
        a = self.numpy()
        return a.item(*idx) if idx else a.item()

    def tolist(self):
        return self.numpy().tolist()

    def _is_initialized(self):
        return True

    # -- value rebinding (in-place family) -------------------------------
    def _rebind(self, value, node=None):
        if node is not None and node is not self._node:
            if any(t is self for t in getattr(node, "inputs", ())):
                # in-place op on a tensor that feeds its own producing node
                # (y.reshape_() where y is non-leaf): snapshot the pre-state
                # under the OLD uid so backward sees old-value -> node -> new
                # instead of a self-cycle
                old = Tensor(self._value, stop_gradient=self.stop_gradient)
                old._node = self._node
                old._uid, self._uid = self._uid, old._uid
                node.inputs = tuple(
                    old if t is self else t for t in node.inputs
                )
            # retarget the (single) output uid to THIS tensor so backward's
            # uid chain stays intact across the rebind
            if getattr(node, "out_uids", None) is not None and                     len(node.out_uids) == 1:
                node.out_uids = (self._uid,)
            self._node = node
        self._value = value
        return self

    def set_value(self, value):
        v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {v.shape} vs {self._value.shape}"
            )
        self._value = v.astype(self._value.dtype)
        return self

    def copy_(self, other):
        return self.set_value(other)

    # -- misc dunders (math dunders patched by paddle_tpu.tensor) --------
    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of a multi-element Tensor is ambiguous"
            )
        return bool(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __repr__(self):
        sg = self.stop_gradient
        if isinstance(self._value, _Tracer):
            return f"Tensor(shape={self.shape}, dtype={_dtypes.dtype_name(self.dtype)}, traced, stop_gradient={sg})"
        return (
            f"Tensor(shape={self.shape}, dtype={_dtypes.dtype_name(self.dtype)}, "
            f"stop_gradient={sg},\n       {np.asarray(self._value)})"
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # numpy interop
    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def is_tracer_value(v) -> bool:
    return isinstance(v, _Tracer)


# Pytree registration: a Tensor flattens to its value; metadata rides along.
def _t_flatten(t: Tensor):
    return (t._value,), (t.stop_gradient, t.name)


def _t_unflatten(aux, children):
    t = Tensor(children[0], stop_gradient=aux[0], name=aux[1])
    return t


jax.tree_util.register_pytree_node(Tensor, _t_flatten, _t_unflatten)
