"""Device management (paddle.device parity).

Reference: ``python/paddle/device/`` (SURVEY.md §2.2). On TPU, placement is
owned by PJRT/jax; set_device selects the default jax device. CUDA-named
entry points are kept for script compatibility and map to the TPU device
(per BASELINE.json's north star: scripts run unchanged with set_device('tpu')).
"""
from __future__ import annotations

import jax

from ..framework.core import CPUPlace, Place, TPUPlace

_current = None


def _platform_devices(kind: str):
    try:
        return jax.devices("cpu" if kind == "cpu" else None)
    except RuntimeError:
        return jax.devices()


def set_device(device: str):
    """paddle.set_device parity: 'tpu', 'tpu:0', 'cpu', 'gpu:0'→tpu."""
    global _current
    kind, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if kind in ("gpu", "cuda", "xpu", "npu"):
        kind = "tpu"
    if kind == "cpu":
        devs = jax.devices("cpu")
    else:
        devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    dev = devs[min(idx, len(devs) - 1)]
    jax.config.update("jax_default_device", dev)
    _current = f"{kind}:{idx}"
    return Place(kind, idx)


def get_device() -> str:
    if _current is not None:
        return _current
    d = jax.devices()[0]
    return ("cpu" if d.platform == "cpu" else "tpu") + f":{d.id}"


def get_all_custom_device_type():
    return ["tpu"]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_distribute() -> bool:
    return True


def device_count() -> int:
    return len([d for d in jax.devices() if d.platform != "cpu"]) or len(jax.devices())


class _Event:
    """Stream event parity shim. XLA's static schedule replaces explicit
    stream/event management (reference: paddle/fluid/platform streams)."""

    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._t = None

    def record(self, stream=None):
        import time

        jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
        self._t = time.perf_counter()

    def synchronize(self):
        pass

    def query(self):
        return True

    def elapsed_time(self, end):
        return (end._t - self._t) * 1000.0 if self._t and end._t else 0.0


class _Stream:
    def __init__(self, device=None, priority=None):
        pass

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        e = event or _Event()
        e.record()
        return e


def synchronize(device=None):
    """Block until all queued device work completes."""
    for d in jax.live_arrays() if hasattr(jax, "live_arrays") else []:
        try:
            d.block_until_ready()
        except Exception:
            break
    (jax.device_put(0) + 0).block_until_ready()


class cuda:
    """paddle.device.cuda compatibility namespace (maps to the TPU device)."""

    Event = _Event
    Stream = _Stream

    @staticmethod
    def synchronize(device=None):
        synchronize(device)

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def empty_cache():
        pass  # PJRT owns the allocator

    @staticmethod
    def memory_allocated(device=None):
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: None)()
        return int(stats.get("bytes_in_use", 0)) if stats else 0

    @staticmethod
    def max_memory_allocated(device=None):
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: None)()
        return int(stats.get("peak_bytes_in_use", 0)) if stats else 0

    @staticmethod
    def memory_reserved(device=None):
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: None)()
        return int(stats.get("bytes_limit", 0)) if stats else 0

    @staticmethod
    def max_memory_reserved(device=None):
        return cuda.memory_reserved(device)

    @staticmethod
    def get_device_properties(device=None):
        d = jax.devices()[0]

        class _Props:
            name = getattr(d, "device_kind", "tpu")
            major, minor = 0, 0
            total_memory = cuda.memory_reserved()
            multi_processor_count = 1

        return _Props()

    @staticmethod
    def get_device_name(device=None):
        return getattr(jax.devices()[0], "device_kind", "tpu")

    @staticmethod
    def get_device_capability(device=None):
        return (0, 0)


class tpu:
    """First-class TPU namespace: device stats straight from PJRT."""

    synchronize = staticmethod(synchronize)
    Event = _Event
    Stream = _Stream

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def memory_stats(device=None):
        d = jax.devices()[0]
        return getattr(d, "memory_stats", lambda: {})() or {}


def host_memory_stats() -> dict:
    """Host staging-arena stats from the native runtime (csrc allocator);
    the host-side analogue of paddle.device.cuda.memory_stats."""
    from .. import runtime

    return runtime.host_memory_stats()


def get_all_device_type():
    """Device types this build can drive (paddle.device.get_all_device_type)."""
    import jax

    out = ["cpu"]
    try:
        if jax.default_backend() == "tpu":
            out.append("tpu")
    except Exception:
        pass
    return out


def get_available_device():
    """Device strings currently visible (paddle.device.get_available_device)."""
    import jax

    try:
        return [f"{d.platform}:{d.id}" for d in jax.devices()]
    except Exception:
        return ["cpu:0"]


# paddle.device.Stream / Event parity (reference: python/paddle/device/
# __init__.py). On TPU there are no user-managed streams — XLA owns the
# schedule — so these are the same API-complete no-op classes the cuda/tpu
# sub-namespaces expose.
Stream = _Stream
Event = _Event


def stream_guard(stream):
    import contextlib

    return contextlib.nullcontext(stream)


def current_stream(device=None):
    return _Stream(device)
