"""paddle_tpu.ops — hand-written TPU kernels (Pallas).

Reference analogue (SURVEY.md §2.1 "PHI kernels"): Paddle hand-writes CUDA
kernels per op; here XLA generates almost everything and Pallas covers only
the ops XLA can't fuse optimally — flash attention, ring attention, MoE
grouped matmul (SURVEY.md §7 step 8).
"""
from . import pallas  # noqa: F401
