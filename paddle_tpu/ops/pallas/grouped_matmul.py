"""Grouped (per-expert) matmul with DYNAMIC group sizes — Pallas TPU kernel
(megablox-style).

Reference capability (SURVEY.md §2.3 "EP / MoE": grouped expert FFN over
`global_scatter`/`global_gather`; §7 step 8 "MoE grouped matmul + ragged
all_to_all"). The reference's experts run as separate CUDA GEMMs per expert;
the TPU-native design is ONE kernel over group-sorted rows:

    out[r] = lhs[r] @ rhs[group_of(r)]    lhs: [M, K], rhs: [G, K, N]

`group_sizes` is a RUNTIME array (routing is data-dependent — this is what
makes dropless MoE possible): rows are sorted by group, groups are ragged,
and a row tile may span several group boundaries. The kernel runs over a
precomputed *visit schedule*: each visit is (row-tile, group) with the
group's row-range inside the tile; boundary tiles get one visit per
overlapping group, with rows outside the visit's range masked before the
MXU dot. The schedule (int32 [V, 8]) is computed in-graph from group_sizes
and rides the scalar-prefetch channel, so the expert-weight BlockSpec index
map can select rhs[group] per visit without any HBM gather.

Rows past sum(group_sizes) are padding: their tiles are visited with an
empty row-range and emit zeros.

Backward with the SAME schedule (visits are simultaneously consecutive in
row-tile AND in group, because rows are group-sorted):
  dlhs = gmm(dout, rhs^T)            (same forward kernel)
  drhs[g] = lhs_g^T @ dout_g         (accumulate per group, emit at each
                                      group's last visit)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU module imports fine on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_M = 128
# N auto-pads to a block multiple inside grouped_matmul, so a wide default
# is safe for any n; measured on v5e it is ~6% faster than 128 at MoE-FFN
# shapes (the lhs block is reused across the whole N sweep)
DEFAULT_BLOCK_N = 1024

# schedule columns
_MTILE, _GID, _RS, _RE, _FIRST_OUT, _LAST_OUT, _FIRST_G, _LAST_G = range(8)


def _build_schedule(group_sizes, m, block_m, num_groups):
    """int32 [V, 8] visit table; V = nt + G + 1 static (worst case: every
    group adds one boundary visit, plus one virtual padding-tail group)."""
    nt = m // block_m
    sizes = jnp.asarray(group_sizes, jnp.int32)
    total = jnp.sum(sizes)
    # virtual tail group absorbs padding rows [total, m) with an EMPTY
    # row-range (those tiles emit zeros)
    sizes_ext = jnp.concatenate([sizes, (m - total)[None]])
    start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(sizes_ext)[:-1]]
    )
    end = start + sizes_ext
    ts = start // block_m
    te = jnp.maximum(-(-end // block_m), ts + 1)  # >= 1 visit even if empty
    vg = te - ts  # visits per group
    voff = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(vg)[:-1]])
    n_visits = voff[-1] + vg[-1]

    v = jnp.arange(nt + num_groups + 1, dtype=jnp.int32)
    gid = jnp.searchsorted(jnp.cumsum(vg), v, side="right").astype(jnp.int32)
    gid = jnp.minimum(gid, num_groups)  # incl. virtual tail
    m_tile = jnp.clip(ts[gid] + (v - voff[gid]), 0, max(nt - 1, 0))
    valid = v < n_visits
    # row range of this visit's group inside its tile (tile-relative)
    rs = jnp.clip(start[gid] - m_tile * block_m, 0, block_m)
    re = jnp.clip(end[gid] - m_tile * block_m, 0, block_m)
    is_tail = gid >= num_groups
    rs = jnp.where(valid & ~is_tail, rs, 0)
    re = jnp.where(valid & ~is_tail, re, 0)
    # padding visits (v >= n_visits) chain onto the last real tile/group so
    # the first/last flags below stay consistent
    m_tile = jnp.where(valid, m_tile, max(nt - 1, 0))
    gid_sched = jnp.where(valid, jnp.minimum(gid, num_groups - 1),
                          num_groups - 1)

    prev_tile = jnp.concatenate([m_tile[:1] - 1, m_tile[:-1]])
    next_tile = jnp.concatenate([m_tile[1:], m_tile[-1:] + 1])
    prev_g = jnp.concatenate([gid_sched[:1] - 1, gid_sched[:-1]])
    next_g = jnp.concatenate([gid_sched[1:], gid_sched[-1:] + 1])
    first_out = (m_tile != prev_tile).astype(jnp.int32)
    last_out = (m_tile != next_tile).astype(jnp.int32)
    first_g = (gid_sched != prev_g).astype(jnp.int32)
    last_g = (gid_sched != next_g).astype(jnp.int32)
    return jnp.stack(
        [m_tile, gid_sched, rs, re, first_out, last_out, first_g, last_g],
        axis=1,
    )


def _require_pltpu():
    if pltpu is None:  # pragma: no cover
        raise NotImplementedError(
            "grouped_matmul needs jax.experimental.pallas.tpu (scalar "
            "prefetch grid spec)"
        )


def _mask_rows(x, rs, re):
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where((rows >= rs) & (rows < re), x, jnp.zeros_like(x))


def _fwd_kernel(sched_ref, lhs_ref, rhs_ref, out_ref, acc):
    v = pl.program_id(1)

    @pl.when(sched_ref[v, _FIRST_OUT] == 1)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    x = _mask_rows(lhs_ref[...], sched_ref[v, _RS], sched_ref[v, _RE])
    acc[...] += jax.lax.dot_general(
        x, rhs_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(sched_ref[v, _LAST_OUT] == 1)
    def _emit():
        out_ref[...] = acc[...].astype(out_ref.dtype)


def _gmm_forward(lhs, rhs, sched, block_m, block_n, interpret):
    _require_pltpu()
    m, k = lhs.shape
    _, k2, n = rhs.shape
    assert k == k2, (lhs.shape, rhs.shape)
    assert m % block_m == 0, f"M={m} must be a block_m={block_m} multiple"
    block_n = min(block_n, n)
    assert n % block_n == 0, f"N={n} must be a block_n={block_n} multiple"
    grid = (n // block_n, sched.shape[0])  # visits innermost

    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, k), lambda j, v, s: (s[v, _MTILE], 0)),
            pl.BlockSpec((1, k, block_n), lambda j, v, s: (s[v, _GID], 0, j)),
        ],
        out_specs=pl.BlockSpec(
            (block_m, block_n), lambda j, v, s: (s[v, _MTILE], j)
        ),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
    )
    return pl.pallas_call(
        _fwd_kernel,
        grid_spec=spec,
        out_shape=jax.ShapeDtypeStruct((m, n), lhs.dtype),
        interpret=interpret,
    )(sched, lhs, rhs)


def _drhs_kernel(sched_ref, lhs_ref, dout_ref, drhs_ref, acc):
    v = pl.program_id(1)

    @pl.when(sched_ref[v, _FIRST_G] == 1)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    x = _mask_rows(lhs_ref[...], sched_ref[v, _RS], sched_ref[v, _RE])
    acc[...] += jax.lax.dot_general(
        x, dout_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(sched_ref[v, _LAST_G] == 1)
    def _emit():
        drhs_ref[0] = acc[...].astype(drhs_ref.dtype)


def _gmm_drhs(lhs, dout, sched, num_groups, block_m, block_n, interpret):
    _require_pltpu()
    m, k = lhs.shape
    n = dout.shape[1]
    block_n = min(block_n, n)
    grid = (n // block_n, sched.shape[0])
    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, k), lambda j, v, s: (s[v, _MTILE], 0)),
            pl.BlockSpec((block_m, block_n),
                         lambda j, v, s: (s[v, _MTILE], j)),
        ],
        out_specs=pl.BlockSpec(
            (1, k, block_n), lambda j, v, s: (s[v, _GID], 0, j)
        ),
        scratch_shapes=[pltpu.VMEM((k, block_n), jnp.float32)],
    )
    return pl.pallas_call(
        _drhs_kernel,
        grid_spec=spec,
        out_shape=jax.ShapeDtypeStruct((num_groups, k, n), jnp.float32),
        interpret=interpret,
    )(sched, lhs, dout)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _gmm(lhs, rhs, sched, num_groups, block_m, block_n, interpret):
    return _gmm_forward(lhs, rhs, sched, block_m, block_n, interpret)


def _gmm_fwd(lhs, rhs, sched, num_groups, block_m, block_n, interpret):
    out = _gmm_forward(lhs, rhs, sched, block_m, block_n, interpret)
    return out, (lhs, rhs, sched)


def _gmm_bwd(num_groups, block_m, block_n, interpret, res, dout):
    lhs, rhs, sched = res
    rhs_t = jnp.swapaxes(rhs, 1, 2)  # [G, N, K]
    dlhs = _gmm_forward(dout, rhs_t, sched, block_m, block_n, interpret)
    drhs = _gmm_drhs(
        lhs, dout, sched, rhs.shape[0], block_m, block_n, interpret
    )
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), None


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


def grouped_matmul(lhs, rhs, group_sizes, block_m=DEFAULT_BLOCK_M,
                   block_n=DEFAULT_BLOCK_N, interpret=None):
    """out[rows of group g] = lhs[rows of group g] @ rhs[g], ragged groups.

    Args:
      lhs: [M, K] rows sorted by group (group-contiguous); M must be a
        block_m multiple. Rows past sum(group_sizes) are padding and
        produce zero rows in the output.
      rhs: [G, K, N] per-group weights.
      group_sizes: [G] int array — may be a traced (data-dependent) value;
        sum(group_sizes) <= M.
    Returns out: [M, N].
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m = lhs.shape[0]
    n = rhs.shape[2]
    num_groups = int(rhs.shape[0])
    # pick the widest block that divides N (wide blocks measured faster on
    # v5e), falling back to 128-col padding at most — padding all the way
    # to a 1024 multiple would compute up to ~78% throwaway columns for
    # N like 1152. The slice below routes the cotangent back through the
    # zero-padding in backward automatically.
    bn = min(block_n, n)
    if n % bn:
        for cand in (512, 256, 128):
            if cand < bn and n % cand == 0:
                bn = cand
                break
        else:
            bn = min(128, bn)
    pad_n = (-n) % bn
    if pad_n:
        rhs = jnp.pad(rhs, ((0, 0), (0, 0), (0, pad_n)))
    sched = _build_schedule(group_sizes, m, block_m, num_groups)
    out = _gmm(
        lhs, rhs, sched, num_groups, int(block_m), int(bn), bool(interpret),
    )
    return out[:, :n] if pad_n else out
