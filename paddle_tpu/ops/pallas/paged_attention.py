"""Fused paged-attention decode/verify — Pallas TPU kernel.

The serving decode hot loop above this kernel (paged KV pool, speculative
verify, int8 KV wire) is shape-static; the einsum reference path
(``paddle_tpu/nn/functional/attention.py::_paged_attention_op``) pays for
that by materializing the gathered K/V pages as f32 ``[S, Hkv, MP*P, D]``
tensors plus a dense ``[S, Hkv, G, T, MP*P]`` logits tensor in HBM every
step, and — under int8 KV — by a separate whole-pool dequant pass.

This kernel fuses the whole per-(slot, kv-head) pipeline into one Pallas
program:

  * page-table-aware gather: the K/V pool blocks are addressed through a
    scalar-prefetched page table (``pltpu.PrefetchScalarGridSpec``), so
    pages stream HBM→VMEM at their STORED dtype and the gathered f32
    copies never exist;
  * GQA-native query folding: the G query heads sharing a kv head ride in
    the kernel's row dimension (``rows = T * G``) — kv heads are never
    replicated in HBM;
  * online (streaming) softmax across the page grid dimension: running
    max / denominator / accumulator live in VMEM scratch, so no
    ``[.., MP*P]`` logits tensor is written to HBM;
  * fused int8 dequant: when per-[page, head] absmax scales are passed,
    ``int8 * scale`` happens on the VMEM-resident page right before the
    QK / PV dots — the f32 pool is never materialized;
  * decode (T=1) and speculative verify (T=k+1) are the SAME kernel: all
    k+1 draft positions score in one pass, each row masked at its own
    causal horizon ``start_position + t``.

The einsum op remains the bit-equality reference oracle: greedy argmax
must agree everywhere (tests/test_pallas_attention.py), raw outputs agree
to f32 tolerance (online vs dense softmax differ in ulps only).

Runs everywhere via ``interpret=True`` (default off-TPU), per the repo's
robustness rule that every Pallas call site declares an interpret-mode
fallback (scripts/check_robustness.py).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is optional at import time (CPU test runs)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


def mask_fill_value(dtype=jnp.float32) -> float:
    """Dtype-aware masked-logit fill, shared by the einsum oracle and the
    Pallas kernel so masked-logit semantics cannot drift between paths.

    Half of ``finfo.min``: large enough that ``exp(fill - row_max)``
    underflows to exactly 0.0 for any realistic logit (so masked keys
    contribute nothing to either the dense or the online softmax), while
    ``fill - row_max`` and the online-softmax rescale ``exp(m_prev - m_new)``
    stay finite even when a row is still all-masked (m_prev == fill).
    """
    return float(jnp.finfo(jnp.dtype(dtype)).min) * 0.5


def available() -> bool:
    """True when the pallas TPU grid-spec machinery imported (it is also
    what drives interpret mode, so this gates CPU fallback too)."""
    return pltpu is not None


def _ceil8(n):
    return max(8, (n + 7) // 8 * 8)


def _scratch(shape):
    vmem = pltpu.VMEM if pltpu is not None else pl.ANY
    return vmem(shape, jnp.float32)


def _paged_kernel(
    *refs, scale, page_size, num_page_slots, groups, rows, fill, has_scales,
):
    """One grid step = one (slot, kv_head, page_slot) triple.

    Grid is (S, Hkv, MP) with the page dimension innermost; m/l/acc
    scratch carries the online softmax across page slots. Row r of the
    folded query block is (draft position t = r // groups, query head
    h_kv * groups + r % groups); kv positions on page slot j are
    ``j * page_size + offset`` in the sequence's virtual key order —
    exactly the gathered-layout positions the einsum oracle masks.
    """
    if has_scales:
        (pt_ref, sp_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        (pt_ref, sp_ref, q_ref, k_ref, v_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
        ks_ref = vs_ref = None
    del pt_ref  # consumed by the BlockSpec index maps, not the body
    s_idx = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, fill)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0]  # [rows8, d] f32
    k = k_ref[0, 0].astype(jnp.float32)  # [page_size, d]
    v = v_ref[0, 0].astype(jnp.float32)
    if has_scales:
        # fused absmax dequant: int8 page * per-[page, head] scale, on the
        # VMEM-resident block — the f32 pool never exists in HBM
        k = k * ks_ref[0, 0]  # scale block [page_size, 1]
        v = v * vs_ref[0, 0]
    s_log = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [rows8, page_size]

    row = jax.lax.broadcasted_iota(jnp.int32, s_log.shape, 0)
    qpos = sp_ref[s_idx] + row // groups
    kpos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, s_log.shape, 1)
    # causal at each row's own horizon; padding rows (row >= rows) are
    # fully masked and sliced off by the wrapper. Trash/unallocated page
    # slots mask themselves: their virtual positions exceed the horizon.
    mask = jnp.logical_and(kpos <= qpos, row < rows)
    s_log = jnp.where(mask, s_log, fill)

    m_prev = m_scr[:, :1]  # [rows8, 1]
    l_prev = l_scr[:, :1]
    m_cur = jnp.max(s_log, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    # dead rows (still all-masked) would get p = exp(fill - fill) = 1 per
    # key; gate on the raw logit so they contribute l = 0 and emit zeros
    p = jnp.where(s_log > fill * 0.5, jnp.exp(s_log - m_new), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_page_slots - 1)
    def _emit():
        safe = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / safe).astype(o_ref.dtype)


def paged_attention(
    q,
    k_pool,
    v_pool,
    page_table,
    start_position,
    *,
    scale=None,
    k_scales=None,
    v_scales=None,
    interpret=None,
):
    """Fused paged attention over a page-table-indirected KV pool.

    Args:
        q: ``[S, T, H, D]`` queries — T=1 for plain decode, T=k+1 for
            speculative verify (all draft positions scored in one pass).
        k_pool, v_pool: ``[N, Hkv, P, D]`` page pools in their STORED
            dtype (f32, bf16, or int8 when scales are passed).
        page_table: ``[S, MP]`` int32 — page slot j of sequence s lives
            in physical page ``page_table[s, j]`` (0 = trash page).
        start_position: ``[S]`` int32 — tokens already cached per slot;
            draft position t attends keys ``<= start_position + t``.
        scale: logit scale; defaults to ``1/sqrt(D)``.
        k_scales, v_scales: optional ``[N, Hkv, P]`` f32 absmax scales —
            passing them turns on fused int8 dequant (both or neither).
        interpret: force pallas interpret mode; default: interpret
            everywhere except on a real TPU backend.

    Returns:
        ``[S, T, H, D]`` f32 attention output.
    """
    if pltpu is None:  # pragma: no cover - pltpu ships with jax
        raise RuntimeError(
            "pallas TPU grid specs unavailable; use the einsum path "
            "(PADDLE_TPU_ATTN_KERNEL=einsum)")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be passed together")
    s, t, h, d = q.shape
    n, hkv, p, _ = k_pool.shape
    mp = page_table.shape[1]
    if h % hkv:
        raise ValueError(f"num heads {h} not divisible by kv heads {hkv}")
    groups = h // hkv
    rows = t * groups
    rows8 = _ceil8(rows)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    sc = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    fill = mask_fill_value(jnp.float32)

    # GQA-native folding: [S, T, H, D] -> [S, Hkv, T*G, D]; the G query
    # heads of a kv head travel as kernel rows, so kv pages are read once
    # per kv head — never replicated across query heads.
    qg = q.astype(jnp.float32).reshape(s, t, hkv, groups, d)
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(s, hkv, rows, d)
    if rows8 != rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rows8 - rows), (0, 0)))

    def q_index(s_i, h_i, j, pt_ref, sp_ref):
        return (s_i, h_i, 0, 0)

    def pool_index(s_i, h_i, j, pt_ref, sp_ref):
        # the page-table gather: grid step (s, h, j) streams physical
        # page pt[s, j] for kv head h straight from the pool
        return (pt_ref[s_i, j], h_i, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, rows8, d), q_index),
        pl.BlockSpec((1, 1, p, d), pool_index),
        pl.BlockSpec((1, 1, p, d), pool_index),
    ]
    args = [qg, k_pool, v_pool]
    has_scales = k_scales is not None
    if has_scales:
        # trailing singleton dim: per-row stats blocks must keep their
        # last two dims equal to the array dims for Mosaic tiling
        in_specs.append(pl.BlockSpec((1, 1, p, 1), pool_index))
        in_specs.append(pl.BlockSpec((1, 1, p, 1), pool_index))
        args.append(k_scales.astype(jnp.float32).reshape(n, hkv, p, 1))
        args.append(v_scales.astype(jnp.float32).reshape(n, hkv, p, 1))

    kernel = functools.partial(
        _paged_kernel, scale=sc, page_size=p, num_page_slots=mp,
        groups=groups, rows=rows, fill=fill, has_scales=has_scales,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, hkv, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows8, d), q_index),
        scratch_shapes=[
            _scratch((rows8, 128)),
            _scratch((rows8, 128)),
            _scratch((rows8, d)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((s, hkv, rows8, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), start_position.astype(jnp.int32), *args)
    out = out[:, :, :rows]
    return out.reshape(s, hkv, t, groups, d).transpose(
        0, 2, 1, 3, 4).reshape(s, t, h, d)
