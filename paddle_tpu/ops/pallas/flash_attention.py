"""Flash attention — Pallas TPU kernel.

Reference capability (SURVEY.md §2.3 "CP" row, §5 "Long-context"): Paddle
wraps the external flashattn CUDA library
(`paddle/phi/kernels/gpu/flash_attn_kernel.cu`,
`python/paddle/nn/functional/flash_attention.py`).

TPU-native design: an online-softmax blockwise kernel (the flash-attention
recurrence) written in Pallas. Q/K/V blocks stream HBM→VMEM per grid step;
the MXU does the [block_q, d] x [d, block_k] logits and [block_q, block_k] x
[block_k, d] accumulation in fp32; running max/denominator live in VMEM
scratch across the innermost (key) grid dimension. Causal masking skips
whole key blocks above the diagonal (predicated with pl.when), so compute is
~halved for causal LM — the same tiling strategy as splash attention.

Backward: jax.custom_vjp whose bwd differentiates the jnp reference (XLA
fuses it well); a dedicated bwd kernel is a later optimization.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is optional at import time (CPU test runs)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _fa_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, seq_len: int, block_q: int, block_k: int,
    num_k_blocks: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: key block strictly above the diagonal contributes nothing
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else (ki >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_idx = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_idx < seq_len
        if causal:
            mask = jnp.logical_and(mask, k_idx <= q_idx)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [block_q, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _emit():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _fa_forward(q, k, v, causal: bool, scale: float, block_q: int, block_k: int, interpret: bool):
    """q,k,v: [BH, T, D] → o: [BH, T, D]."""
    bh, t, d = q.shape
    block_q = min(block_q, max(t, 8))
    block_k = min(block_k, max(t, 8))
    pad_q = (-t) % block_q
    pad_k = (-t) % block_k
    tq, tk = t + pad_q, t + pad_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    nq, nk = tq // block_q, tk // block_k

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, seq_len=t,
        block_q=block_q, block_k=block_k, num_k_blocks=nk,
    )
    vmem = pltpu.VMEM if pltpu is not None else pl.ANY
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            vmem((block_q, 128), jnp.float32),
            vmem((block_q, 128), jnp.float32),
            vmem((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :t] if pad_q else out


def _reference(q, k, v, causal, scale):
    # [BH, T, D] reference used only for the backward pass
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = s.shape[-1]
        cm = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(cm, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fa(q, k, v, causal, scale, interpret):
    return _fa_forward(q, k, v, causal, scale, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K, interpret)


def _fa_fwd(q, k, v, causal, scale, interpret):
    return _fa(q, k, v, causal, scale, interpret), (q, k, v)


def _fa_bwd(causal, scale, interpret, res, do):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _reference(a, b, c, causal, scale), q, k, v)
    return vjp(do)


_fa.defvjp(_fa_fwd, _fa_bwd)


def flash_attention(q, k, v, causal: bool = False, scale=None):
    """q, k, v: [B, T, H, D] (paddle flash-attention layout) → [B, T, H, D]."""
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    interpret = jax.default_backend() != "tpu"

    def fold(x):
        return jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)

    o = _fa(fold(q), fold(k), fold(v), bool(causal), float(scale), interpret)
    return jnp.swapaxes(o.reshape(b, h, t, d), 1, 2)
