"""Flash attention — Pallas TPU kernels (forward AND backward).

Reference capability (SURVEY.md §2.3 "CP" row, §5 "Long-context"): Paddle
wraps the external flashattn CUDA library
(`paddle/phi/kernels/gpu/flash_attn_kernel.cu` and
`flash_attn_grad_kernel.cu`, exposed via
`python/paddle/nn/functional/flash_attention.py`).

TPU-native design: online-softmax blockwise kernels written in Pallas.
Q/K/V blocks stream HBM→VMEM per grid step; the MXU does the
[block_q, d] x [d, block_k] logits and the [block_q, block_k] x [block_k, d]
accumulation in fp32; running max/denominator live in VMEM scratch across
the innermost grid dimension.

Causal block skipping is done in the BlockSpec index maps, not just with
pl.when: grid steps whose K/V block lies entirely above the diagonal have
their index map clamped to the last valid block, and Pallas elides the
HBM→VMEM copy when consecutive steps map to the same block — so dead blocks
cost neither bandwidth nor MXU time (compute is additionally gated with
pl.when).

Backward is the standard recompute-based flash backward: the forward also
emits the per-row logsumexp (LSE); backward recomputes P = exp(S - LSE)
blockwise (no O(T^2) HBM tensor is ever materialized) and accumulates
dQ in one kernel (grid over K blocks innermost) and dK/dV in a second
kernel (grid over Q blocks innermost), all in fp32 VMEM scratch.

Supported: causal (incl. tq != tk, bottom-right aligned), additive bias /
boolean mask broadcastable over batch and head, GQA/MQA (num_kv_heads
divides num_heads), bias gradient.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is optional at import time (CPU test runs)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

# Measured on v5e (fwd TF/s at b8/s2048/h16/d64, causal): blocks 128 -> 4.1,
# 256 -> 6.8, 512 -> 10.2, 1024 -> 12.9 (vs XLA-unfused 8.6, official jax
# pallas kernel at its defaults 5.8). Per-grid-step overhead dominates small
# blocks; 1024 keeps the fp32 logits tile at 4MB of VMEM and is clamped to
# the (padded) sequence length for short inputs.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30


def _env_block(name, default):
    import os

    try:
        v = int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default
    # Mosaic needs the block's second-to-last dim divisible by 8; zero or
    # negative values would divide-by-zero in the pad math
    return _ceil8(v)


def _blocks_fwd():
    """Forward block sizes; env-tunable (PADDLE_TPU_FLASH_BLOCK_Q/K) for
    on-chip sweeps. Read at TRACE time: a changed env var does not retrace
    an already-compiled shape — sweep in fresh processes."""
    bq = _env_block("PADDLE_TPU_FLASH_BLOCK_Q", DEFAULT_BLOCK_Q)
    bk = _env_block("PADDLE_TPU_FLASH_BLOCK_K", DEFAULT_BLOCK_K)
    return bq, bk


def _blocks_bwd():
    """Backward block sizes; default to the forward's, separately tunable
    (PADDLE_TPU_FLASH_BWD_BLOCK_Q/K) — the bwd kernel's working set is
    ~2.5x the fwd's per tile, so its optimum can sit one size lower."""
    fq, fk = _blocks_fwd()
    bq = _env_block("PADDLE_TPU_FLASH_BWD_BLOCK_Q", fq)
    bk = _env_block("PADDLE_TPU_FLASH_BWD_BLOCK_K", fk)
    return bq, bk


def _ceil8(n):
    return max(8, (n + 7) // 8 * 8)


def _scratch(shape):
    vmem = pltpu.VMEM if pltpu is not None else pl.ANY
    return vmem(shape, jnp.float32)


def _causal_run(qi, ki, block_q, block_k, tq, tk):
    """kv block `ki` overlaps q block `qi`'s visible region (bottom-right
    aligned). Single source of truth for every pl.when gate; the index-map
    clamps below are its inverse images, so gates and clamps cannot drift."""
    return ki * block_k <= qi * block_q + block_q - 1 + (tk - tq)


def _causal_last_kv(qi, block_q, block_k, tq, tk, nk):
    """Largest kv block with _causal_run(qi, ki) true (clamped to grid)."""
    last = (qi * block_q + block_q - 1 + (tk - tq)) // block_k
    return jnp.minimum(nk - 1, jnp.maximum(last, 0))


def _causal_first_q(ki, block_q, block_k, tq, tk, nq):
    """Smallest q block with _causal_run(qi, ki) true (clamped to grid)."""
    first = (ki * block_k - (tk - tq)) // block_q
    return jnp.minimum(jnp.maximum(first, 0), nq - 1)


def _mask_for(qi, ki, block_q, block_k, tq, tk, causal, shape):
    """Validity mask for a [block_q, block_k] logits tile."""
    q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    k_idx = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = jnp.logical_and(k_idx < tk, q_idx < tq)
    if causal:
        mask = jnp.logical_and(mask, k_idx <= q_idx + (tk - tq))
    return mask


def _logits(q, k, scale, bias_ref):
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    return s


# ---------------------------------------------------------------- forward

def _fwd_kernel(
    *refs, scale, causal, tq, tk, block_q, block_k, num_k_blocks, has_bias,
):
    if has_bias:
        q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        bias_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: a key block strictly above the diagonal contributes nothing
    run = _causal_run(qi, ki, block_q, block_k, tq, tk) if causal else (ki >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        s = _logits(q, k, scale, bias_ref)
        mask = _mask_for(qi, ki, block_q, block_k, tq, tk, causal, s.shape)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [block_q, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # dead rows (all keys at NEG_INF, e.g. a fully-masked-out query via a
        # bool-mask-folded bias) would get p = exp(s - m_new) = 1 for EVERY
        # key; gate on the raw logit so they contribute l = 0 and emit zeros
        p = jnp.where(s > NEG_INF * 0.5, jnp.exp(s - m_new), 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _emit():
        l = l_scr[:, :1]
        safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_scr[:] / safe).astype(o_ref.dtype)
        # lse_ref block is [1, block_q, 1]: per-row stats travel with a
        # trailing singleton dim because Mosaic requires a block's last two
        # dims to be (divisible by 8, divisible by 128) OR equal to the
        # array dims — a (1, block_q) row block is rejected on real TPU
        # (interpret mode does not enforce this)
        lse_ref[0] = jnp.where(l > 0, m_scr[:, :1] + jnp.log(safe), NEG_INF)


def _bh_kv(b, n_heads, n_kv_heads):
    """Flattened-[batch*head] index → flattened-[batch*kv_head] index."""
    group = n_heads // n_kv_heads
    return b // n_heads * n_kv_heads + (b % n_heads) // group


def _bh_bias(b, n_heads, bias_b, bias_h):
    return (b // n_heads) % bias_b * bias_h + (b % n_heads) % bias_h


def _make_index_maps(causal, tq, tk, nq, nk, block_q, block_k, n_heads,
                     n_kv_heads, bias_b, bias_h, bias_tq, bias_tk):
    """Shared K/V + bias BlockSpec index maps with the causal diagonal clamp.

    Grid steps whose K/V block is entirely above the diagonal are clamped to
    the last valid block; Pallas elides the HBM copy for repeated indices,
    so dead blocks cost no bandwidth. Used identically by the forward and
    the dQ backward so their block-skipping can never diverge.

    Bias pages keep singleton broadcast dims (batch/head via _bh_bias,
    Tq/Tk by pinning the block index to 0) so a (B,1,1,Tk) padding mask is
    never materialized to O(B*H*Tq*Tk).
    """

    def kv_index(b, i, j):
        bkv = _bh_kv(b, n_heads, n_kv_heads)
        if causal:
            j = jnp.minimum(j, _causal_last_kv(i, block_q, block_k, tq, tk, nk))
        return (bkv, j, 0)

    def bias_index(b, i, j):
        _, jj, _ = kv_index(b, i, j)
        return (
            _bh_bias(b, n_heads, bias_b, bias_h),
            i if bias_tq > 1 else 0,
            jj if bias_tk > 1 else 0,
        )

    return kv_index, bias_index


def _bias_block(block_q, block_k, bias_tq, bias_tk):
    return (1, block_q if bias_tq > 1 else 1, block_k if bias_tk > 1 else 1)


def _pad_bias(bias, pad_q, pad_k):
    return jnp.pad(bias, (
        (0, 0),
        (0, pad_q if bias.shape[1] > 1 else 0),
        (0, pad_k if bias.shape[2] > 1 else 0),
    ))


def _fa_forward(q, k, v, bias, causal, scale, n_heads, n_kv_heads,
                bias_b, bias_h, block_q, block_k, interpret):
    """q: [B*H, Tq, D]; k,v: [B*Hkv, Tk, D]; bias: [Bb*Hb, Tq, Tk] or None.

    Returns (o [B*H, Tq, D], lse [B*H, Tq_padded] fp32).
    """
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, _ceil8(tq))
    block_k = min(block_k, _ceil8(tk))
    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    bias_tq = bias.shape[1] if bias is not None else 1
    bias_tk = bias.shape[2] if bias is not None else 1
    if bias is not None and (pad_q or pad_k):
        bias = _pad_bias(bias, pad_q, pad_k)
    nq, nk = (tq + pad_q) // block_q, (tk + pad_k) // block_k

    kv_index, bias_index = _make_index_maps(
        causal, tq, tk, nq, nk, block_q, block_k, n_heads, n_kv_heads,
        bias_b, bias_h, bias_tq, bias_tk,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec(_bias_block(block_q, block_k, bias_tq, bias_tk),
                         bias_index)
        )
        args.append(bias)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, tq=tq, tk=tk,
        block_q=block_q, block_k=block_k, num_k_blocks=nk,
        has_bias=bias is not None,
    )
    o, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq + pad_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq + pad_q, 1), jnp.float32),
        ],
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            _scratch((block_q, 128)),
            _scratch((block_q, 128)),
            _scratch((block_q, d)),
        ],
        interpret=interpret,
    )(*args)
    return (o[:, :tq] if pad_q else o), lse[:, :, 0]


# ---------------------------------------------------------------- backward

def _bwd_p_ds(q, k, v, do, lse, delta, bias_ref, mask, scale):
    """Recompute P and dS for one [block_q, block_k] tile (all fp32)."""
    s = _logits(q, k, scale, bias_ref)
    # the s-threshold gate mirrors the forward: dead rows (lse == NEG_INF,
    # s ~= NEG_INF) must recompute p = 0, not exp(s - lse) = 1
    p = jnp.where(
        jnp.logical_and(mask, s > NEG_INF * 0.5), jnp.exp(s - lse), 0.0
    )  # lse: [block_q, 1]
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta)  # delta: [block_q, 1]
    return p, ds


def _dq_kernel(
    *refs, scale, causal, tq, tk, block_q, block_k, num_k_blocks, has_bias,
    has_dbias,
):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    bias_ref = refs[i] if has_bias else None
    i += int(has_bias)
    do_ref, lse_ref, delta_ref, dq_ref = refs[i:i + 4]
    i += 4
    dbias_ref = refs[i] if has_dbias else None
    acc_scr = refs[-1]
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _causal_run(qi, ki, block_q, block_k, tq, tk) if causal else (ki >= 0)

    @pl.when(run)
    def _step():
        mask = _mask_for(qi, ki, block_q, block_k, tq, tk, causal,
                         (block_q, block_k))
        _, ds = _bwd_p_ds(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0].astype(jnp.float32),
            lse_ref[0], delta_ref[0], bias_ref, mask, scale,
        )
        if dbias_ref is not None:
            dbias_ref[0] = ds.astype(dbias_ref.dtype)
        acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    if dbias_ref is not None:
        @pl.when(jnp.logical_not(run))
        def _dead_bias():
            dbias_ref[0] = jnp.zeros_like(dbias_ref[0])

    @pl.when(ki == num_k_blocks - 1)
    def _emit():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    *refs, scale, causal, tq, tk, block_q, block_k, num_q_blocks, has_bias,
):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        bias_ref = None
    ki = pl.program_id(1)
    qj = pl.program_id(2)

    @pl.when(qj == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _causal_run(qj, ki, block_q, block_k, tq, tk) if causal else (qj >= 0)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        mask = _mask_for(qj, ki, block_q, block_k, tq, tk, causal,
                         (block_q, block_k))
        p, ds = _bwd_p_ds(
            q_ref[0], k_ref[0], v_ref[0], do,
            lse_ref[0], delta_ref[0], bias_ref, mask, scale,
        )
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale

    @pl.when(qj == num_q_blocks - 1)
    def _emit():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _fa_backward(q, k, v, bias, o, lse, do, causal, scale, n_heads,
                 n_kv_heads, bias_b, bias_h, bias_grad, block_q, block_k,
                 interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, _ceil8(tq))
    block_k = min(block_k, _ceil8(tk))
    pad_q = (-tq) % block_q
    pad_k = (-tk) % block_k
    tqp, tkp = tq + pad_q, tk + pad_k

    # delta_i = rowsum(dO * O) — tiny elementwise reduce; let XLA fuse it.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
        do = jnp.pad(do, ((0, 0), (0, pad_q), (0, 0)))
        delta = jnp.pad(delta, ((0, 0), (0, pad_q)))
        # lse is produced padded by the forward
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    bias_tq = bias.shape[1] if bias is not None else 1
    bias_tk = bias.shape[2] if bias is not None else 1
    if bias is not None and (pad_q or pad_k):
        bias = _pad_bias(bias, pad_q, pad_k)
    if lse.shape[1] < tqp:
        lse = jnp.pad(lse, ((0, 0), (0, tqp - lse.shape[1])))
    elif lse.shape[1] > tqp:
        # residual lse is padded to the FORWARD block grid, which can be
        # wider than the backward's when bwd blocks are tuned smaller
        lse = lse[:, :tqp]
    # per-row stats enter the kernels with a trailing singleton dim (see
    # the forward's lse out_spec for the Mosaic tiling rule)
    lse = lse[:, :, None]
    delta = delta[:, :, None]
    nq, nk = tqp // block_q, tkp // block_k
    has_bias = bias is not None
    # dbias needs a per-(batch*q-head) [Tq, Tk] dS tensor in HBM — O(B*H*T^2),
    # far beyond the bias itself. Only pay it when the bias actually needs a
    # gradient (mask-derived biases never do).
    want_dbias = has_bias and bias_grad

    # ---- dQ: grid (bh, q blocks, k blocks innermost)
    kv_index, bias_index = _make_index_maps(
        causal, tq, tk, nq, nk, block_q, block_k, n_heads, n_kv_heads,
        bias_b, bias_h, bias_tq, bias_tk,
    )
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    in_specs = [
        q_spec,
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    args = [q, k, v]
    if has_bias:
        in_specs.append(
            pl.BlockSpec(_bias_block(block_q, block_k, bias_tq, bias_tk),
                         bias_index)
        )
        args.append(bias)
    in_specs += [q_spec, row_spec, row_spec]
    args += [do, lse, delta]

    out_shape = [jax.ShapeDtypeStruct((bh, tqp, d), q.dtype)]
    out_specs = [q_spec]
    if want_dbias:
        out_shape.append(jax.ShapeDtypeStruct((bh, tqp, tkp), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, block_k), lambda b, i, j: (b, i, j))
        )

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, causal=causal, tq=tq, tk=tk,
        block_q=block_q, block_k=block_k, num_k_blocks=nk, has_bias=has_bias,
        has_dbias=want_dbias,
    )
    dq_out = pl.pallas_call(
        dq_kernel,
        out_shape=out_shape,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(*args)
    if want_dbias:
        dq, ds_full = dq_out
        dbias = ds_full[:, :tq, :tk].reshape(
            bh // n_heads, n_heads, tq, tk
        )
        if bias_b == 1:
            dbias = dbias.sum(0, keepdims=True)
        if bias_h == 1:
            dbias = dbias.sum(1, keepdims=True)
        if bias_tq == 1:
            dbias = dbias.sum(2, keepdims=True)
        if bias_tk == 1:
            dbias = dbias.sum(3, keepdims=True)
        dbias = dbias.reshape(bias_b * bias_h, bias_tq, bias_tk)
    else:
        (dq,) = dq_out
        dbias = None
    dq = dq[:, :tq]

    # ---- dK/dV: grid (bh over *q heads*, k blocks, q blocks innermost);
    # GQA: per-q-head partials are group-summed after the kernel.
    def kv_index2(b, i, j):
        return (_bh_kv(b, n_heads, n_kv_heads), i, 0)

    def q_index2(b, i, j):
        if causal:
            j = jnp.maximum(j, _causal_first_q(i, block_q, block_k, tq, tk, nq))
        return (b, j, 0)

    def row_index2(b, i, j):
        _, jj, _ = q_index2(b, i, j)
        return (b, jj, 0)

    in_specs2 = [
        pl.BlockSpec((1, block_q, d), q_index2),
        pl.BlockSpec((1, block_k, d), kv_index2),
        pl.BlockSpec((1, block_k, d), kv_index2),
    ]
    args2 = [q, k, v]
    if has_bias:
        def bias_index2(b, i, j):
            _, jj, _ = q_index2(b, i, j)
            return (
                _bh_bias(b, n_heads, bias_b, bias_h),
                jj if bias_tq > 1 else 0,
                i if bias_tk > 1 else 0,
            )

        in_specs2.append(
            pl.BlockSpec(_bias_block(block_q, block_k, bias_tq, bias_tk),
                         bias_index2)
        )
        args2.append(bias)
    in_specs2 += [
        pl.BlockSpec((1, block_q, d), q_index2),
        pl.BlockSpec((1, block_q, 1), row_index2),
        pl.BlockSpec((1, block_q, 1), row_index2),
    ]
    args2 += [do, lse, delta]

    kv_out_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0))
    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, causal=causal, tq=tq, tk=tk,
        block_q=block_q, block_k=block_k, num_q_blocks=nq, has_bias=has_bias,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tkp, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tkp, d), jnp.float32),
        ],
        grid=(bh, nk, nq),
        in_specs=in_specs2,
        out_specs=[kv_out_spec, kv_out_spec],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=interpret,
    )(*args2)
    dk, dv = dk[:, :tk], dv[:, :tk]
    group = n_heads // n_kv_heads
    if group > 1:
        batch = bh // n_heads
        dk = dk.reshape(batch, n_kv_heads, group, tk, d).sum(2).reshape(-1, tk, d)
        dv = dv.reshape(batch, n_kv_heads, group, tk, d).sum(2).reshape(-1, tk, d)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), dbias


# ---------------------------------------------------------------- custom vjp

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _fa(q, k, v, bias, causal, scale, n_heads, n_kv_heads, bias_b, bias_h,
        bias_grad, interpret):
    o, _ = _fa_forward(
        q, k, v, bias, causal, scale, n_heads, n_kv_heads, bias_b, bias_h,
        *_blocks_fwd(), interpret,
    )
    return o


def _fa_fwd(q, k, v, bias, causal, scale, n_heads, n_kv_heads, bias_b,
            bias_h, bias_grad, interpret):
    o, lse = _fa_forward(
        q, k, v, bias, causal, scale, n_heads, n_kv_heads, bias_b, bias_h,
        *_blocks_fwd(), interpret,
    )
    return o, (q, k, v, bias, o, lse)


def _fa_bwd(causal, scale, n_heads, n_kv_heads, bias_b, bias_h, bias_grad,
            interpret, res, do):
    q, k, v, bias, o, lse = res
    dq, dk, dv, dbias = _fa_backward(
        q, k, v, bias, o, lse, do, causal, scale, n_heads, n_kv_heads,
        bias_b, bias_h, bias_grad, *_blocks_bwd(), interpret,
    )
    if bias is None:
        dbias = None
    elif dbias is None:  # bias present but bias_grad=False: zero cotangent
        dbias = jnp.zeros_like(bias)
    else:
        dbias = dbias.astype(bias.dtype)
    return dq, dk, dv, dbias


_fa.defvjp(_fa_fwd, _fa_bwd)


# ---------------------------------------------------------------- public API

def flash_attention(q, k, v, causal: bool = False, scale=None, bias=None,
                    mask=None, bias_needs_grad: bool = True):
    """Blockwise (flash) attention.

    Args:
      q: [B, Tq, H, D] (paddle flash-attention layout).
      k, v: [B, Tk, Hkv, D]; Hkv may divide H (GQA/MQA).
      causal: bottom-right-aligned causal masking.
      scale: logits scale, default 1/sqrt(D).
      bias: additive logits bias, [B|1, H|1, Tq|1, Tk|1]. Broadcast
        (singleton) dims are honored inside the kernel via the BlockSpec
        index maps — a (B,1,1,Tk) padding mask stays O(B*Tk) in HBM.
      mask: boolean keep-mask, same broadcastable shape; folded into bias
        (never differentiated).
      bias_needs_grad: set False for non-trained biases — the dbias pass
        materializes an O(B*H*Tq*Tk) buffer that is then skipped entirely.

    Query rows with no visible keys (causal with Tq > Tk, or a fully-masked
    row) return zeros (the reference dense softmax would produce NaN).

    Returns [B, Tq, H, D].
    """
    b, tq, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv != 0:
        raise ValueError(f"num_heads {h} not divisible by num_kv_heads {hkv}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    interpret = jax.default_backend() != "tpu"

    bias_grad = bias_needs_grad and bias is not None
    if mask is not None:
        neg = jnp.asarray(NEG_INF, jnp.float32)
        m = jnp.where(mask, 0.0, neg)
        bias = m if bias is None else bias + m

    bias_b = bias_h = 1
    if bias is not None:
        bias = jnp.asarray(bias)
        if bias.ndim != 4:
            raise ValueError(f"bias must be rank-4, got {bias.shape}")
        bias_b, bias_h = int(bias.shape[0]), int(bias.shape[1])
        if bias_b not in (1, b) or bias_h not in (1, h):
            raise ValueError(
                f"bias dims ({bias_b}, {bias_h}) must broadcast over "
                f"batch={b} / heads={h} (per-kv-head bias pages are "
                "unsupported)"
            )
        if (bias.shape[2] not in (1, tq)
                or bias.shape[3] not in (1, k.shape[1])):
            raise ValueError(
                f"bias seq dims {bias.shape[2:]} must broadcast over "
                f"(Tq={tq}, Tk={k.shape[1]})"
            )
        # merge batch/head pages; keep Tq/Tk singleton dims un-materialized
        bias = bias.reshape(bias_b * bias_h, bias.shape[2], bias.shape[3])

    def fold(x):
        return jnp.swapaxes(x, 1, 2).reshape(-1, x.shape[1], x.shape[-1])

    o = _fa(
        fold(q), fold(k), fold(v), bias, bool(causal), float(scale),
        h, hkv, bias_b, bias_h, bias_grad, interpret,
    )
    return jnp.swapaxes(o.reshape(b, h, tq, d), 1, 2)
