from . import flash_attention  # noqa: F401
from . import paged_attention  # noqa: F401
