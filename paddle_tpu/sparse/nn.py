"""paddle.sparse.nn — layers and functionals over SparseCooTensor.

Reference capability: ``python/paddle/sparse/nn/`` (Conv3D / SubmConv3D /
BatchNorm / activations / MaxPool3D, functional conv3d / subm_conv3d /
max_pool3d), whose GPU path gathers rulebooks and scatters through cuSPARSE
kernels. TPU-native design: the MXU wants dense tiles, so sparse 3-D convs
compute on the densified block (XLA conv, which IS the fast path on TPU for
the occupancy regimes the reference targets) and carry the sparse STRUCTURE
exactly: a regular conv3d's output sites are the input sites dilated by the
kernel support (computed by convolving the occupancy indicator with an
all-ones kernel); a submanifold conv keeps the input sites unchanged.
Values at structural sites are kept even when numerically zero — same
contract as the reference's rulebook output.

Input layout [N, D, H, W, C] (channel-last, the reference's only supported
sparse conv layout); kernel layout [kD, kH, kW, C_in/groups, C_out].
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from .. import nn as dense_nn
from ..framework.core import Tensor
from ..framework.op import raw
from ..nn import functional as F
from ..nn import initializer as I

from . import SparseTensor, _as_bcoo, relu  # noqa: F401  (re-export relu)

__all__ = [
    "Conv3D", "SubmConv3D", "BatchNorm", "MaxPool3D",
    "ReLU", "ReLU6", "LeakyReLU", "Softmax", "functional",
]


def _coo_from_dense_at(dense, sites_nd, sparse_shape):
    """COO over explicit structural ``sites_nd`` [nnz, ndim-1] (values may
    be zero there — structure is semantic, not derived from magnitude)."""
    vals = dense[tuple(sites_nd.T)]
    mat = jsparse.BCOO(
        (vals, jnp.asarray(sites_nd, jnp.int32)), shape=tuple(sparse_shape)
    )
    return SparseTensor(mat, "coo")


def _sites(x: SparseTensor) -> np.ndarray:
    """Unique (n, d, h, w) active sites of a [N,D,H,W,C] sparse input.

    Accepts both storage conventions: 4 sparse dims with dense [C] values
    (the reference layout) and 5 fully-sparse dims (what ``to_sparse``
    yields) — the channel column is dropped for the site set.
    """
    xb = x._mat.sum_duplicates() if x._fmt == "coo" else x._mat.to_bcoo().sum_duplicates()
    idx = np.asarray(xb.indices)
    if idx.shape[1] not in (4, 5):
        raise ValueError(
            "sparse conv expects a [N, D, H, W, C] SparseCooTensor; got "
            f"{idx.shape[1]} sparse dims"
        )
    return np.unique(idx[:, :4], axis=0)


def _triple(v):
    return (v,) * 3 if isinstance(v, int) else tuple(v)


def _structure_indicator(x: SparseTensor, dense_shape):
    """Float [N,D,H,W,1] with 1.0 at every STORED site of ``x``."""
    ind = np.zeros(tuple(dense_shape[:4]) + (1,), np.float32)
    ind[tuple(_sites(x).T)] = 1.0
    return jnp.asarray(ind)


def _conv3d_impl(x, weight, bias, stride, padding, dilation, groups, subm):
    if not isinstance(x, SparseTensor):
        raise TypeError("sparse conv3d expects a SparseCooTensor input")
    w = raw(weight) if hasattr(weight, "_value") or hasattr(weight, "numpy") else jnp.asarray(weight)
    if w.ndim != 5:
        raise ValueError("kernel must be [kD, kH, kW, C_in/groups, C_out]")
    stride, dilation = _triple(stride), _triple(dilation)
    if subm and (any(s != 1 for s in stride)):
        raise ValueError("subm_conv3d requires stride 1 (sites must be preserved)")

    dense = x.to_dense()  # Tensor [N, D, H, W, C]
    # paddle dense conv kernel layout is [C_out, C_in/groups, kD, kH, kW]
    w_dense = Tensor(jnp.transpose(w, (4, 3, 0, 1, 2)))
    y = F.conv3d(
        dense, w_dense, bias=bias, stride=stride, padding=padding,
        dilation=dilation, groups=groups, data_format="NDHWC",
    )
    yv = raw(y)

    if subm:
        sites = _sites(x)
    else:
        # occupancy indicator (1 at STORED sites — structure, not value
        # magnitude: a structurally-stored exact-zero value still occupies
        # its site) convolved with an all-ones kernel marks every site the
        # kernel support can reach — the reference rulebook's structure
        occ_in = _structure_indicator(x, raw(dense).shape)
        kD, kH, kW = w.shape[:3]
        ones_w = Tensor(jnp.ones((1, 1, kD, kH, kW), jnp.float32))
        occ = F.conv3d(
            Tensor(occ_in), ones_w, stride=stride, padding=padding,
            dilation=dilation, data_format="NDHWC",
        )
        sites = np.argwhere(np.asarray(raw(occ))[..., 0] > 0)
    return _coo_from_dense_at(yv, sites, yv.shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", key=None, name=None):
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d supports NDHWC only (matches paddle)")
    return _conv3d_impl(x, weight, bias, stride, padding, dilation, groups, False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d supports NDHWC only (matches paddle)")
    return _conv3d_impl(x, weight, bias, stride, padding, dilation, groups, True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d supports NDHWC only (matches paddle)")
    if not isinstance(x, SparseTensor):
        raise TypeError("sparse max_pool3d expects a SparseCooTensor input")
    dense = raw(x.to_dense())
    occ_in = _structure_indicator(x, dense.shape)
    # the reference pools STORED values only: implicit zeros must not win
    # (an all-negative window pools to its largest stored value, not 0), so
    # empty positions are masked to -inf before the dense pooling
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, dense.dtype)
    masked = jnp.where(occ_in > 0, dense, neg)
    y = F.max_pool3d(
        Tensor(masked), kernel_size, stride=stride, padding=padding,
        ceil_mode=ceil_mode, data_format="NDHWC",
    )
    yv = raw(y)
    occ = F.max_pool3d(
        Tensor(occ_in), kernel_size, stride=stride, padding=padding,
        ceil_mode=ceil_mode, data_format="NDHWC",
    )
    sites = np.argwhere(np.asarray(raw(occ))[..., 0] > 0)
    return _coo_from_dense_at(yv, sites, yv.shape)


class _SparseUnaryLayer(dense_nn.Layer):
    def forward(self, x: SparseTensor) -> SparseTensor:
        xb = _as_bcoo(x)
        return SparseTensor(
            jsparse.BCOO((self._fn(xb.data), xb.indices), shape=xb.shape), "coo"
        )


class ReLU(_SparseUnaryLayer):
    _fn = staticmethod(lambda v: jnp.maximum(v, 0))


class ReLU6(_SparseUnaryLayer):
    _fn = staticmethod(lambda v: jnp.clip(v, 0, 6))


class LeakyReLU(_SparseUnaryLayer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = float(negative_slope)

    def forward(self, x):
        xb = _as_bcoo(x)
        v = jnp.where(xb.data >= 0, xb.data, self._slope * xb.data)
        return SparseTensor(jsparse.BCOO((v, xb.indices), shape=xb.shape), "coo")


class Softmax(dense_nn.Layer):
    def __init__(self, axis=-1):
        super().__init__()
        from . import _SparseNN

        self._impl = _SparseNN.Softmax(axis)

    def forward(self, x):
        return self._impl(x)


class Conv3D(dense_nn.Layer):
    """y = sparse_conv3d(x, W) over [N,D,H,W,C]; kernel stored in the
    reference layout [kD,kH,kW,C_in/groups,C_out]."""

    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if padding_mode != "zeros":
            raise NotImplementedError("sparse conv: zeros padding only")
        kD, kH, kW = _triple(kernel_size)
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        fan_in = in_channels // groups * kD * kH * kW
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            (kD, kH, kW, in_channels // groups, out_channels),
            attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound),
        )
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-bound, bound),
            )

    def forward(self, x):
        fn = subm_conv3d if self._subm else conv3d
        return fn(x, self.weight, bias=self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation,
                  groups=self._groups)


class SubmConv3D(Conv3D):
    """Submanifold conv: output sites == input sites (stride 1)."""

    _subm = True


class MaxPool3D(dense_nn.Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format="NDHWC"):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        k, s, p, cm = self._args
        return max_pool3d(x, k, stride=s, padding=p, ceil_mode=cm)


class BatchNorm(dense_nn.Layer):
    """Channel-wise batch norm over the STORED values only (the reference
    normalizes nnz values, not the implicit zeros)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum, self._eps = float(momentum), float(epsilon)
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self._mean = jnp.zeros((num_features,), jnp.float32)
        self._variance = jnp.ones((num_features,), jnp.float32)

    def forward(self, x: SparseTensor) -> SparseTensor:
        xb = _as_bcoo(x).sum_duplicates()
        v = xb.data
        nc = self._mean.shape[0]
        if v.ndim == 2:
            # reference layout: 4 sparse site dims, dense [C] values
            chan = None
            v32 = v.astype(jnp.float32)
        elif v.ndim == 1:
            # fully-sparse storage (to_sparse): channel is the last index
            # column; per-channel stats via segment reductions
            chan = xb.indices[:, -1].astype(jnp.int32)
            v32 = v.astype(jnp.float32)
        else:
            raise ValueError("sparse BatchNorm expects [*, C] or scalar values")
        use_global = (
            self._use_global_stats
            if self._use_global_stats is not None
            else not self.training
        )
        if use_global:
            mean, var = self._mean, self._variance
        else:
            if chan is None:
                mean = v32.mean(0)
                var = v32.var(0)
            else:
                cnt = jnp.zeros(nc, jnp.float32).at[chan].add(1.0)
                safe = jnp.maximum(cnt, 1.0)
                mean = jnp.zeros(nc, jnp.float32).at[chan].add(v32) / safe
                var = jnp.zeros(nc, jnp.float32).at[chan].add(
                    (v32 - mean[chan]) ** 2) / safe
            m = self._momentum
            self._mean = m * self._mean + (1 - m) * mean
            self._variance = m * self._variance + (1 - m) * var
        inv = 1.0 / jnp.sqrt(var + self._eps)
        scale = (raw(self.weight) * inv).astype(v.dtype)
        shift = raw(self.bias).astype(v.dtype)
        if chan is None:
            out = (v - mean.astype(v.dtype)) * scale + shift
        else:
            out = (v - mean[chan].astype(v.dtype)) * scale[chan] + shift[chan]
        return SparseTensor(
            jsparse.BCOO((out, xb.indices), shape=xb.shape), "coo")


class functional:  # paddle.sparse.nn.functional namespace parity
    conv3d = staticmethod(conv3d)
    subm_conv3d = staticmethod(subm_conv3d)
    max_pool3d = staticmethod(max_pool3d)
    relu = staticmethod(relu)
