"""paddle.sparse parity — COO/CSR sparse tensors and sparse ops.

Reference: ``paddle/phi/core/sparse_coo_tensor.h`` / ``sparse_csr_tensor.h``
and the ``paddle.sparse`` Python API (``python/paddle/sparse/``): creation
(sparse_coo_tensor / sparse_csr_tensor), conversion (to_dense/to_sparse_coo),
elementwise ops, matmul, and sparse activations (SURVEY.md §2.1 "PHI core":
SparseCooTensor). TPU-native design: storage is ``jax.experimental.sparse``
BCOO/BCSR, whose ops lower to XLA gather/scatter/dot_general — so sparse
compute stays on-device and composes with jit/grad. XLA has no true sparse
MXU path; for the block-sparse attention case use the Pallas kernels in
``paddle_tpu.ops.pallas`` instead (that is the TPU-idiomatic answer for hot
sparse compute; this module covers API/semantics parity).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor
from ..framework.dtypes import convert_dtype


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseTensor:
    """Common wrapper over BCOO (coo) / BCSR (csr) with paddle's surface."""

    def __init__(self, mat, fmt: str):
        self._mat = mat
        self._fmt = fmt

    # --- paddle.Tensor sparse surface ---
    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    def nnz(self):
        return int(self._mat.nse)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return self._fmt == "coo"

    def is_sparse_csr(self):
        return self._fmt == "csr"

    def indices(self):
        if self._fmt != "coo":
            raise ValueError("indices() is for COO; use crows()/cols()")
        return Tensor(self._mat.indices.T)  # paddle layout: [ndim, nnz]

    def values(self):
        return Tensor(self._mat.data)

    def crows(self):
        if self._fmt != "csr":
            raise ValueError("crows() is for CSR")
        return Tensor(self._mat.indptr)

    def cols(self):
        if self._fmt != "csr":
            raise ValueError("cols() is for CSR")
        return Tensor(self._mat.indices)

    def to_dense(self) -> Tensor:
        return Tensor(self._mat.todense())

    def to_sparse_coo(self, sparse_dim=None) -> "SparseTensor":
        if self._fmt == "coo":
            return self
        return SparseTensor(self._mat.to_bcoo(), "coo")

    def to_sparse_csr(self) -> "SparseTensor":
        if self._fmt == "csr":
            return self
        return SparseTensor(jsparse.BCSR.from_bcoo(self._mat), "csr")

    def coalesce(self) -> "SparseTensor":
        if self._fmt != "coo":
            return self
        return SparseTensor(self._mat.sum_duplicates(), "coo")

    # arithmetic sugar
    def __matmul__(self, other):
        return matmul(self, other)

    def __add__(self, other):
        return add(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __repr__(self):
        return f"SparseTensor(fmt={self._fmt}, shape={self.shape}, nnz={self.nnz()})"


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    """paddle.sparse.sparse_coo_tensor: indices [sparse_ndim, nnz], values [nnz, ...]."""
    idx = _val(indices).astype(jnp.int32)
    vals = _val(values)
    if dtype is not None:
        vals = vals.astype(convert_dtype(dtype))
    if idx.ndim != 2:
        raise ValueError("indices must be [sparse_ndim, nnz]")
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1)) + vals.shape[1:]
    mat = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    return SparseTensor(mat, "coo")


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    vals = _val(values)
    if dtype is not None:
        vals = vals.astype(convert_dtype(dtype))
    mat = jsparse.BCSR(
        (vals, _val(cols).astype(jnp.int32), _val(crows).astype(jnp.int32)),
        shape=tuple(shape),
    )
    return SparseTensor(mat, "csr")


def to_sparse(t, fmt="coo"):
    """Dense Tensor → SparseTensor (paddle: Tensor.to_sparse_coo())."""
    dense = _val(t)
    coo = jsparse.BCOO.fromdense(dense)
    st = SparseTensor(coo, "coo")
    return st if fmt == "coo" else st.to_sparse_csr()


def _as_bcoo(x):
    if isinstance(x, SparseTensor):
        return x._mat if x._fmt == "coo" else x._mat.to_bcoo()
    raise TypeError("expected SparseTensor")


# ---------------------------------------------------------------------------
# ops (python/paddle/sparse/binary.py, unary.py)
# ---------------------------------------------------------------------------
def matmul(x: SparseTensor, y) -> Tensor:
    """sparse @ dense → dense (the main sparse compute path)."""
    if isinstance(y, SparseTensor):
        out = _as_bcoo(x) @ _as_bcoo(y)
        return SparseTensor(out, "coo")
    return Tensor(_as_bcoo(x) @ _val(y))


def masked_matmul(x, y, mask: SparseTensor) -> SparseTensor:
    """dense @ dense evaluated only at mask's nonzero positions (SDDMM)."""
    xm, ym = _val(x), _val(y)
    m = _as_bcoo(mask).sum_duplicates()
    rows, cols_ = m.indices[:, 0], m.indices[:, 1]
    vals = (xm[rows] * ym[:, cols_].T).sum(-1)
    return SparseTensor(jsparse.BCOO((vals, m.indices), shape=m.shape), "coo")


def add(x: SparseTensor, y: SparseTensor) -> SparseTensor:
    out = (_as_bcoo(x) + _as_bcoo(y)).sum_duplicates()
    return SparseTensor(out, "coo")


def subtract(x: SparseTensor, y: SparseTensor) -> SparseTensor:
    yb = _as_bcoo(y)
    neg = jsparse.BCOO((-yb.data, yb.indices), shape=yb.shape)
    return SparseTensor((_as_bcoo(x) + neg).sum_duplicates(), "coo")


def multiply(x: SparseTensor, y) -> SparseTensor:
    if isinstance(y, SparseTensor):
        # elementwise product of two sparse operands via sparsify
        f = jsparse.sparsify(lambda a, b: a * b)
        return SparseTensor(f(_as_bcoo(x), _as_bcoo(y)), "coo")
    xb = _as_bcoo(x)
    yv = _val(y)
    if yv.ndim == 0:
        return SparseTensor(jsparse.BCOO((xb.data * yv, xb.indices), shape=xb.shape), "coo")
    vals = xb.data * yv[tuple(xb.indices[:, i] for i in range(xb.indices.shape[1]))]
    return SparseTensor(jsparse.BCOO((vals, xb.indices), shape=xb.shape), "coo")


def divide(x: SparseTensor, y) -> SparseTensor:
    xb = _as_bcoo(x)
    yv = _val(y)
    if yv.ndim == 0:
        return SparseTensor(jsparse.BCOO((xb.data / yv, xb.indices), shape=xb.shape), "coo")
    vals = xb.data / yv[tuple(xb.indices[:, i] for i in range(xb.indices.shape[1]))]
    return SparseTensor(jsparse.BCOO((vals, xb.indices), shape=xb.shape), "coo")


def transpose(x: SparseTensor, perm: Sequence[int]) -> SparseTensor:
    return SparseTensor(_as_bcoo(x).transpose(tuple(perm)), "coo")


def reshape(x: SparseTensor, shape: Sequence[int]) -> SparseTensor:
    """paddle.sparse.reshape parity: remap COO coordinates through the flat
    index (structure-exact — no densify; one -1 wildcard as in dense
    reshape)."""
    import numpy as _np

    xb = _as_bcoo(x)
    old = tuple(int(s) for s in xb.shape)
    new = [int(s) for s in shape]
    if new.count(-1) > 1:
        raise ValueError("reshape accepts at most one -1")
    total = int(_np.prod(old))
    if -1 in new:
        known = int(_np.prod([s for s in new if s != -1]))
        new[new.index(-1)] = total // known
    if int(_np.prod(new)) != total:
        raise ValueError(f"cannot reshape {old} -> {tuple(shape)}")
    def strides(dims):
        out = [1]
        for d in reversed(dims[1:]):
            out.append(out[-1] * int(d))
        return list(reversed(out))

    idx_dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    if total >= 2 ** 31 and not jax.config.jax_enable_x64:
        raise ValueError(
            f"sparse.reshape: dense size {total} exceeds int32 flat-index "
            "range; enable jax_enable_x64 for >2^31-element sparse shapes")
    old_strides = jnp.asarray(strides(old), idx_dtype)
    flat = (xb.indices.astype(idx_dtype) * old_strides[None, :]).sum(axis=1)
    idx_cols = [(flat // st) % int(d) for st, d in zip(strides(new), new)]
    indices = jnp.stack(idx_cols, axis=1).astype(xb.indices.dtype)
    return SparseTensor(
        jsparse.BCOO((xb.data, indices), shape=tuple(new)), "coo")


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _unary(fn):
    def op(x: SparseTensor) -> SparseTensor:
        xb = _as_bcoo(x)
        return SparseTensor(jsparse.BCOO((fn(xb.data), xb.indices), shape=xb.shape), "coo")

    return op


# value-wise unaries that preserve sparsity (f(0)=0), as in paddle.sparse
relu = _unary(jax.nn.relu)
sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
abs = _unary(jnp.abs)
expm1 = _unary(jnp.expm1)
log1p = _unary(jnp.log1p)
neg = _unary(jnp.negative)


def pow(x: SparseTensor, factor) -> SparseTensor:
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x: SparseTensor, index_dtype=None, value_dtype=None) -> SparseTensor:
    xb = _as_bcoo(x)
    data = xb.data if value_dtype is None else xb.data.astype(convert_dtype(value_dtype))
    idx = xb.indices if index_dtype is None else xb.indices.astype(convert_dtype(index_dtype))
    return SparseTensor(jsparse.BCOO((data, idx), shape=xb.shape), "coo")


class _SparseNN:
    """paddle.sparse.nn subset: activation layers over SparseTensor."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    class Softmax:
        """Softmax over the last dim, only at stored positions, for any rank
        (paddle.sparse.nn.Softmax semantics; the reference also supports only
        axis=-1). Leading dims are fused into one segment key so a single
        segment-max/segment-sum pair handles 2D and ND alike."""

        def __init__(self, axis=-1):
            if axis != -1:
                raise NotImplementedError(
                    "sparse softmax: axis=-1 only (matches paddle.sparse)")

        def __call__(self, x):
            xb = _as_bcoo(x).sum_duplicates()
            lead = xb.shape[:-1]
            rows = jnp.zeros(xb.indices.shape[0], jnp.int32)
            stride = 1
            for d in range(len(lead) - 1, -1, -1):
                rows = rows + xb.indices[:, d].astype(jnp.int32) * stride
                stride *= lead[d]
            nrows = max(stride, 1)
            rowmax = jnp.full(nrows, -jnp.inf, xb.data.dtype).at[rows].max(xb.data)
            e = jnp.exp(xb.data - rowmax[rows])
            denom = jnp.zeros(nrows, xb.data.dtype).at[rows].add(e)
            return SparseTensor(
                jsparse.BCOO((e / denom[rows], xb.indices), shape=xb.shape), "coo"
            )


# real paddle.sparse.nn module (Conv3D/SubmConv3D/BatchNorm/pooling +
# activations); _SparseNN.Softmax above stays the shared softmax impl
from . import nn  # noqa: E402,F401

__all__ = [
    "SparseTensor", "sparse_coo_tensor", "sparse_csr_tensor", "to_sparse",
    "matmul", "masked_matmul", "add", "subtract", "multiply", "divide",
    "transpose", "is_same_shape", "relu", "tanh", "sqrt", "square", "abs",
    "sin", "tan", "asin", "atan", "sinh", "asinh", "atanh", "expm1", "log1p",
    "neg", "pow", "cast", "nn",
]
