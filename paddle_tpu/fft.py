"""paddle.fft parity — discrete Fourier transforms.

Reference: ``python/paddle/fft.py`` (fft/ifft/rfft/…/fftshift over phi FFT
kernels backed by cuFFT). TPU-native: jnp.fft lowers to XLA's FFT HLO, which
runs on-device; norm conventions ("backward"/"ortho"/"forward") match numpy
and the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from .framework.core import Tensor


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap1(fn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return Tensor(fn(_val(x), n=n, axis=axis, norm=norm))

    return op


def _wrap2(fn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return Tensor(fn(_val(x), s=s, axes=axes, norm=norm))

    return op


def _wrapn(fn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return Tensor(fn(_val(x), s=s, axes=axes, norm=norm))

    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)

fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)

fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


# Hermitian 2-D transforms via the identity hfftn(x, s) = irfftn(conj(x), s)
# * prod(s) (numpy/scipy define hfft this way; numpy has no hfft2/hfftn, so
# these are built from jnp primitives and stay jit-traceable). Norm follows
# the forward-transform convention (like fft): backward = unscaled, ortho =
# 1/sqrt(N), forward = 1/N, with N = prod of transformed lengths; ihfft2
# mirrors it (backward = 1/N, ortho = 1/sqrt(N), forward = unscaled).
def _norm_factor(norm, n, op):
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(f"{op}: norm must be backward/ortho/forward, got {norm!r}")
    return {"backward": 1.0, "ortho": float(n) ** 0.5, "forward": float(n)}[norm]


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    xv = _val(x)
    out = jnp.fft.irfftn(jnp.conj(xv), s=s, axes=axes)
    n = 1
    for ax in axes:
        n *= out.shape[ax]
    return Tensor(out * (n / _norm_factor(norm, n, "hfft2")))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    xv = _val(x)
    out = jnp.conj(jnp.fft.rfftn(xv, s=s, axes=axes))
    n = 1
    if s is not None:
        for m in s:
            n *= m
    else:
        for ax in axes:
            n *= xv.shape[ax]
    return Tensor(out * (_norm_factor(norm, n, "ihfft2") / n))


def _default_axes(ndim, s, axes):
    """numpy convention: axes=None means all axes, or the LAST len(s) axes
    when a shorter `s` is given."""
    if axes is not None:
        return tuple(axes)
    if s is not None:
        return tuple(range(ndim - len(s), ndim))
    return tuple(range(ndim))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """n-dim Hermitian FFT (same identity as hfft2, arbitrary axes)."""
    xv = _val(x)
    ax = _default_axes(xv.ndim, s, axes)
    out = jnp.fft.irfftn(jnp.conj(xv), s=s, axes=ax)
    n = 1
    for a in ax:
        n *= out.shape[a]
    return Tensor(out * (n / _norm_factor(norm, n, "hfftn")))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    xv = _val(x)
    ax = _default_axes(xv.ndim, s, axes)
    out = jnp.conj(jnp.fft.rfftn(xv, s=s, axes=ax))
    n = 1
    if s is not None:
        for m in s:
            n *= m
    else:
        for a in ax:
            n *= xv.shape[a]
    return Tensor(out * (_norm_factor(norm, n, "ihfftn") / n))


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d))


def fftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.fftshift(_val(x), axes=axes))


def ifftshift(x, axes=None, name=None):
    return Tensor(jnp.fft.ifftshift(_val(x), axes=axes))


__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]
