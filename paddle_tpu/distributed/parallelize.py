"""paddle.distributed.parallelize / to_distributed parity.

Reference: ``python/paddle/distributed/auto_parallel/intermediate/parallelize.py``
(the 3.x "one-call" parallelization API: a ``parallelize_plan`` maps layer-name
patterns to plan objects like ``ColWiseParallel``) and
``python/paddle/distributed/auto_tuner``-backed ``to_distributed``.

TPU-native design: a plan object only *annotates* parameters with a
PartitionSpec (``p.dist_spec``) and re-places them (``jax.device_put`` with a
NamedSharding). The compiled train step then runs under GSPMD, which inserts
the identity-forward/allreduce-backward (column) and allreduce-forward (row)
collectives the reference implements as hand-written mp layers — no wrapper
layers are needed. Sequence-parallel markers become sharding constraints on
the layer boundary activations.
"""
from __future__ import annotations

import fnmatch
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.op import raw
from ..nn.layer import Layer

__all__ = [
    "ColWiseParallel", "RowWiseParallel", "SequenceParallelBegin",
    "SequenceParallelEnd", "parallelize", "to_distributed",
]


class _PlanBase:
    def apply(self, layer: Layer, jax_mesh, axis: str):
        raise NotImplementedError


class ColWiseParallel(_PlanBase):
    """Column-parallel: shard the weight's OUTPUT dim (and bias) over the
    mp axis. For Embedding the sharded dim is the embedding dim. The
    reference's gather_output gathers the activation; under GSPMD the
    activation sharding is inferred, so the flag only drops the output
    constraint."""

    def __init__(self, gather_output: bool = False):
        self.gather_output = gather_output

    def apply(self, layer, jax_mesh, axis):
        for name, p in layer.named_parameters(include_sublayers=False):
            v = raw(p)
            if v.ndim >= 2:
                spec = P(*([None] * (v.ndim - 1) + [axis]))
            elif v.ndim == 1 and v.shape[0] % jax_mesh.shape[axis] == 0:
                spec = P(axis)  # bias follows the output dim
            else:
                spec = P()
            p.dist_spec = spec
            p._rebind(jax.device_put(v, NamedSharding(jax_mesh, spec)))


class RowWiseParallel(_PlanBase):
    """Row-parallel: shard the weight's INPUT dim over the mp axis; bias
    stays replicated (it adds after the allreduce). For Embedding this is
    vocab-parallel sharding."""

    def __init__(self, is_input_parallel: bool = True):
        self.is_input_parallel = is_input_parallel

    def apply(self, layer, jax_mesh, axis):
        for name, p in layer.named_parameters(include_sublayers=False):
            v = raw(p)
            if v.ndim >= 2:
                spec = P(*([axis] + [None] * (v.ndim - 1)))
            else:
                spec = P()
            p.dist_spec = spec
            p._rebind(jax.device_put(v, NamedSharding(jax_mesh, spec)))


class _SeqParallelMarker(_PlanBase):
    """Constrain the layer-boundary activation to be sequence-sharded (dim 1
    of a [batch, seq, hidden] activation) over the mp axis — the reference's
    Megatron-SP scatter/gather boundary, expressed as a GSPMD constraint."""

    _hook = "pre"  # Begin constrains the input; End the output

    def apply(self, layer, jax_mesh, axis):
        from ..framework.core import Tensor

        def constrain(x):
            if isinstance(x, Tensor) and raw(x).ndim >= 2:
                spec = P(None, axis)
                return Tensor(jax.lax.with_sharding_constraint(
                    raw(x), NamedSharding(jax_mesh, spec)),
                    stop_gradient=x.stop_gradient)
            return x

        if self._hook == "pre":
            layer.register_forward_pre_hook(
                lambda lyr, inputs: tuple(constrain(i) for i in inputs))
        else:
            layer.register_forward_post_hook(
                lambda lyr, inputs, output: constrain(output))


class SequenceParallelBegin(_SeqParallelMarker):
    _hook = "pre"


class SequenceParallelEnd(_SeqParallelMarker):
    _hook = "post"


def _match_layers(model: Layer, pattern: str):
    """fnmatch over sublayer names (the reference uses the same dotted-name
    patterns, e.g. ``llama.layers.*.self_attn.q_proj``)."""
    hits = []
    for name, sub in model.named_sublayers():
        if fnmatch.fnmatchcase(name, pattern):
            hits.append((name, sub))
    if not hits and pattern in ("", "."):
        hits.append(("", model))
    return hits


def parallelize(model: Layer, optimizer=None, mesh=None,
                config: Optional[Dict] = None):
    """Apply a parallelization config to ``model`` in one call.

    ``config`` keys (reference shape):
      - ``mp_config = {"parallelize_plan": {name_pattern: plan | [plans]}}``
        with :class:`ColWiseParallel` / :class:`RowWiseParallel` /
        sequence-parallel markers.
      - ``dp_config = {"sharding_level": 0|1|2|3}`` — levels 1-3 extend each
        param's spec with the ``sharding`` (ZeRO) axis; under one compiled
        SPMD step the three levels place the same param shards, so they
        collapse to "sharded" here (stage differences are an optimizer-state
        placement concern handled by the fleet policies).
      - ``pp_config`` — not supported by this entry point: build the model
        with ``fleet.meta_parallel.SpmdPipeline`` instead (compiled 1F1B).

    Returns ``(model, optimizer)``.
    """
    from . import fleet as _fleet
    from . import mesh as _mesh_mod
    from .auto_parallel import ProcessMesh

    config = config or {}
    if config.get("pp_config"):
        raise NotImplementedError(
            "parallelize(pp_config=...): pipeline stages are built with "
            "fleet.meta_parallel.SpmdPipeline (compiled 1F1B schedules)")

    if mesh is None:
        gm = _mesh_mod.get_global_mesh()
        if gm is None:
            raise ValueError("parallelize: pass a ProcessMesh (or fleet.init "
                             "a global mesh) first")
        jm = gm
    elif isinstance(mesh, ProcessMesh):
        jm = mesh.jax_mesh
    else:
        jm = mesh

    mp_axis = "mp" if "mp" in jm.shape else next(
        (a for a in jm.shape if a not in ("dp", "sharding", "pp", "sep")),
        None)

    plan = (config.get("mp_config") or {}).get("parallelize_plan") or {}
    if plan and (mp_axis is None or jm.shape.get(mp_axis, 1) <= 1):
        raise ValueError(
            f"parallelize: mp_config given but mesh {dict(jm.shape)} has no "
            "model-parallel axis ('mp') larger than 1")
    for pattern, plans in plan.items():
        plans = plans if isinstance(plans, (list, tuple)) else [plans]
        hits = _match_layers(model, pattern)
        if not hits:
            raise ValueError(
                f"parallelize: pattern {pattern!r} matched no sublayer")
        for _name, sub in hits:
            for pl in plans:
                pl.apply(sub, jm, mp_axis)

    level = int((config.get("dp_config") or {}).get("sharding_level", 0))
    if level:
        if "sharding" not in jm.shape or jm.shape["sharding"] <= 1:
            raise ValueError(
                "parallelize: dp_config.sharding_level set but the mesh has "
                "no 'sharding' axis larger than 1")
        for _n, p in model.named_parameters():
            spec = getattr(p, "dist_spec", None) or P()
            spec = _fleet._extend_with_axis(
                spec, tuple(raw(p).shape), "sharding", jm.shape["sharding"])
            p.dist_spec = spec
            p._rebind(jax.device_put(raw(p), NamedSharding(jm, spec)))
    return model, optimizer


def to_distributed(model: Layer, optimizer=None, dataloader=None,
                   device_num: Optional[int] = None,
                   node_num: int = 1, config=None):
    """paddle.distributed.to_distributed parity: pick a parallel strategy
    automatically and apply it.

    The reference auto-tunes over dp/mp/pp candidates with a cost model; on
    TPU the robust default for a model that fits per-device is pure data
    parallel over all devices (collectives ride ICI; GSPMD already overlaps
    the grad reduction), so that is what this applies: a 1-D ``dp`` global
    mesh, replicated parameters, and a batch-sharding dataloader wrapper.
    Models that need mp/pp should call :func:`parallelize` (explicit plan)
    or the fleet hybrid APIs.
    """
    from . import mesh as _mesh_mod
    from .auto_parallel import ProcessMesh, shard_dataloader

    n = device_num or len(jax.devices())
    total = n * max(int(node_num), 1)
    total = min(total, len(jax.devices()))
    pm = ProcessMesh(np.arange(total), dim_names=["dp"])
    _mesh_mod.set_global_mesh(pm.jax_mesh)
    for _n2, p in model.named_parameters():
        p.dist_spec = P()
        p._rebind(jax.device_put(raw(p), NamedSharding(pm.jax_mesh, P())))
    if dataloader is not None:
        dataloader = shard_dataloader(dataloader, pm, shard_dims="dp")
    return model, optimizer, dataloader
