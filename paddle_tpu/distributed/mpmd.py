"""MPMD pipeline execution: one compiled program per stage, async queues between.

The SPMD pipeline (`fleet.meta_parallel.SpmdPipeline`) compiles the whole
1F1B schedule — every stage's forward, backward and the inter-stage
`ppermute` — into ONE XLA program spanning ALL devices. That is the right
default on a homogeneous slice, but it hard-wires two costs:

* **global recompile**: resizing one stage (dp width change after a
  stragglers/elastic event) invalidates the single program, so all S
  stages pay the 4.7-7 s compile (MULTICHIP_SCALING.json `compile_s`);
* **uniform width**: one mesh means every stage gets the same dp x mp
  layout, even when the layer stack is unbalanced (a fat embedding stage
  next to thin decoder stages).

This module is the MPMD path (arXiv:2412.14374 — "Scaling Deep Learning
Training with MPMD Pipeline Parallelism"): each stage owns

* a **device subset** and its own `Mesh` (widths may differ per stage),
* its own **AOT-compiled programs** — `fwd` for non-last stages,
  `bwd` (recompute-in-backward vjp + gradient accumulation) and a
  fused `loss_grad` on the last stage — cached per stage in the
  persistent compile cache under `key_for(..., stage=...)`, so a
  stage-local resize recompiles exactly ONE stage's programs
  (`runtime/compile_cache.py`),
* a pair of **async boundary queues** (activations downstream,
  cotangents upstream) built on the PR 11 streaming-transport frame
  protocol: length-prefixed `tq` frames, per-channel seq dedup
  (`transport.SeqChannels`, channels `act<i>`/`cot<i>`), cumulative
  `tq_ack` watermarks, sender-side replay of unacked frames after a
  reconnect, every blocking socket op under `deadline_guard`
  (scripts/check_robustness.py rule 6).

The per-stage tick driver is the PR 8 **phased schedule table**
(`pipeline_parallel.phased_stage_table`): each stage runner replays
exactly the (tick, F/B, microbatch) op list the SPMD compiled schedule
executes, so 1F1B ordering, warmup depth and microbatch accounting carry
over unchanged — and the MPMD trajectory matches the SPMD one to the
reassociation-only tolerance the tests pin (<=1e-5; bit-equal between
local and TCP transports on the `raw`/`f32` wire).

Boundary tensors are **respec'd, not assumed aligned**: a stage gathers
its output to host, ships it at the configured wire dtype
(PADDLE_TPU_MPMD_WIRE: raw | f32 | bf16 | int8), and the receiver
`device_put`s onto ITS OWN mesh's batch sharding — unequal widths
(dp2 -> dp1, dp1 -> dp3, ...) need no collective bridge program; the
byte cost is priced by `reshard.plan_boundary` and fed to the
auto-parallel planner's per-stage width search.

Failure unit = one stage. Each stage checkpoints its own shard
(`fleet.elastic.save_stage_shard`); after a SIGKILL the driver restores
every stage at `latest_common_step` and replays queues from the last
acked microbatch (`SeqChannels.seek`). docs/PIPELINE.md §MPMD has the
stage program contract, queue/ack semantics and the failure matrix.

This module is the single writer of the ``mpmd_*`` metric/span families
(scripts/check_observability.py enforces that).
"""
from __future__ import annotations

import collections
import os
import queue as _queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .. import observability as _obs
from ..framework.core import Tensor, no_grad
from ..framework.op import raw
from ..runtime.compile_cache import resolve as _resolve_cache
from ..serving import transport as _transport
from ..serving.protocol import deadline_guard
from ..testing import chaos
from . import reshard as _reshard

__all__ = [
    "MpmdPipeline", "MpmdStage", "BoundaryEndpoint",
    "local_boundary", "tcp_boundary",
    "ENV_WIRE", "ENV_STAGES", "resolve_wire",
]

#: wire dtype for boundary tensors (transport.TENSOR_WIRES); `raw`/`f32`
#: are bit-preserving for f32 activations — the trajectory gate's wire
ENV_WIRE = "PADDLE_TPU_MPMD_WIRE"

#: launch CLI exports the per-stage width plan ("dp0,dp1,...") here so a
#: relaunched worker rebuilds the same stage layout it died with
ENV_STAGES = "PADDLE_TPU_MPMD_STAGES"

#: bound on every blocking queue wait (seconds); the deadline guard on
#: the underlying socket ops is the watchdog of last resort
_QUEUE_TIMEOUT = float(os.environ.get("PADDLE_TPU_MPMD_TIMEOUT", "120"))


def resolve_wire(wire: Optional[str] = None) -> str:
    w = wire or os.environ.get(ENV_WIRE, "raw")
    if w not in _transport.TENSOR_WIRES:
        raise ValueError(
            f"{ENV_WIRE}={w!r} not in {_transport.TENSOR_WIRES}")
    return w


def parse_stage_widths(spec: Optional[str] = None) -> Optional[List[int]]:
    """Decode the launch CLI's ENV_STAGES export ("2,2" -> [2, 2])."""
    s = spec if spec is not None else os.environ.get(ENV_STAGES, "")
    if not s:
        return None
    return [int(tok) for tok in s.replace(" ", "").split(",") if tok]


# ---------------------------------------------------------------------------
# Boundary queues: tq frames + per-channel seq + ack/replay
# ---------------------------------------------------------------------------
class _LocalChan:
    """In-process frame pipe (thread-safe), same send/poll surface as the
    TCP chans so the endpoint logic is transport-agnostic."""

    def __init__(self, tx: _queue.Queue, rx: _queue.Queue):
        self._tx, self._rx = tx, rx

    def send(self, frame: dict) -> bool:
        self._tx.put(frame)
        return True

    def poll(self) -> List[dict]:
        out: List[dict] = []
        while True:
            try:
                out.append(self._rx.get_nowait())
            except _queue.Empty:
                return out


def _local_chan_pair() -> Tuple[_LocalChan, _LocalChan]:
    a, b = _queue.Queue(), _queue.Queue()
    return _LocalChan(a, b), _LocalChan(b, a)


class _ServerChan:
    """Downstream side of a TCP boundary: owns the listener. A new
    connection id means the peer redialed — surfaced as a synthetic
    ``_reconnect`` frame so the endpoint replays its unacked tail."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = _transport.TransportServer(host, port)
        self._cid: Optional[int] = None

    @property
    def addr(self) -> str:
        return self._server.addr

    def poll(self) -> List[dict]:
        out: List[dict] = []
        for cid, fr in self._server.poll():
            if cid != self._cid:
                self._cid = cid
                out.append({"t": "_reconnect"})
            out.append(fr)
        return out

    def send(self, frame: dict) -> bool:
        if self._cid is None:
            return False
        return self._server.send(self._cid, frame)


class _ClientChan:
    """Upstream side of a TCP boundary: persistent dialer with jittered
    backoff (transport.TransportClient); a completed redial is surfaced
    as a ``_reconnect`` frame."""

    def __init__(self, addr: str, seed: int = 0):
        self._client = _transport.TransportClient(addr, seed=seed)
        self._seen_reconnects = self._client.reconnects

    def poll(self) -> List[dict]:
        frames = self._client.poll()
        if self._client.reconnects != self._seen_reconnects:
            self._seen_reconnects = self._client.reconnects
            frames = [{"t": "_reconnect"}] + frames
        return frames

    def send(self, frame: dict) -> bool:
        return self._client.send(frame)


def _payload_nbytes(frame: dict) -> int:
    payload = frame.get("x", {})
    n = 0
    for k in ("x", "scale"):
        v = payload.get(k)
        if isinstance(v, np.ndarray):
            n += v.nbytes
    return n


class BoundaryEndpoint:
    """One side of a stage boundary: sends on one tq channel, receives on
    the other, over any chan with a send/poll surface.

    Reliability contract (docs/PIPELINE.md §MPMD):

    * outgoing frames carry per-channel seqs (`SeqChannels.next_seq`) and
      are retained in an unacked buffer until the peer's cumulative
      `tq_ack` watermark covers them;
    * incoming frames dedup against the per-channel cursor — a
      retransmit of a consumed microbatch is dropped, never re-applied;
    * a reconnect (new conn id / redial) replays the whole unacked tail
      in seq order — the receiver's dedup makes that idempotent;
    * `seek()` fast-forwards the consume cursor after a checkpoint
      restore, so replay starts at the last acked microbatch.

    Every chan op sits under ``deadline_guard`` — rule 6 of
    scripts/check_robustness.py enforces it statically.
    """

    def __init__(self, chan, send_channel: str, recv_channel: str, *,
                 wire: str = "raw", timeout: Optional[float] = None):
        self._chan = chan
        self._send_ch = send_channel
        self._recv_ch = recv_channel
        self.wire = wire
        self._seqs = _transport.SeqChannels()
        self._unacked: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self._need_replay = False
        self._timeout = _QUEUE_TIMEOUT if timeout is None else float(timeout)

    # -- sender side --------------------------------------------------------
    def send(self, arr: np.ndarray, *, mb: int, meta: Optional[dict] = None
             ) -> int:
        meta = dict(meta or ())
        meta["mb"] = int(mb)
        seq = self._seqs.next_seq(self._send_ch)
        frame = _transport.encode_tq_frame(
            self._send_ch, seq, np.asarray(arr), wire=self.wire, meta=meta)
        self._unacked[seq] = frame
        with deadline_guard(f"mpmd tq send {self._send_ch}", self._timeout):
            if not self._chan.send(frame):
                self._need_replay = True
        _obs.inc("mpmd_boundary_bytes_total", _payload_nbytes(frame),
                 channel=self._send_ch)
        return seq

    def unacked(self) -> int:
        return len(self._unacked)

    # -- receiver side ------------------------------------------------------
    def seek(self, seq: int) -> None:
        """Checkpoint-restore replay point: consume cursor jumps to the
        last acked microbatch's seq; older retransmits become duplicates."""
        self._seqs.seek(self._recv_ch, int(seq))

    def acked_watermark(self) -> int:
        """Next seq this side will consume (== cumulative ack sent)."""
        return self._seqs.cursor(self._recv_ch)

    def _pump(self) -> None:
        with deadline_guard(f"mpmd tq poll {self._recv_ch}", self._timeout):
            frames = self._chan.poll()
        for fr in frames:
            t = fr.get("t")
            if t == "_reconnect":
                self._need_replay = True
            elif t == "tq" and fr.get("ch") == self._recv_ch:
                self._seqs.stash(self._recv_ch, int(fr["seq"]), fr)
            elif t == "tq_ack" and fr.get("ch") == self._send_ch:
                upto = int(fr["seq"])
                for s in [s for s in self._unacked if s <= upto]:
                    del self._unacked[s]
        if self._need_replay:
            if not self._unacked:
                self._need_replay = False
                return
            ok = True
            for fr in list(self._unacked.values()):
                with deadline_guard(
                        f"mpmd tq replay {self._send_ch}", self._timeout):
                    ok = self._chan.send(fr)
                if not ok:
                    break
            if ok:
                _obs.inc("mpmd_queue_replay_total", channel=self._send_ch)
                _obs.event("mpmd_queue_replay", channel=self._send_ch,
                           frames=len(self._unacked))
                self._need_replay = False

    def recv(self, *, timeout: Optional[float] = None) -> Tuple[np.ndarray,
                                                                dict]:
        """Next in-order frame on the recv channel (bounded block); sends
        the cumulative ack for it before returning."""
        limit = self._timeout if timeout is None else float(timeout)
        t_end = time.monotonic() + limit
        while True:
            fr = self._seqs.pop_next(self._recv_ch)
            if fr is not None:
                seq = int(fr["seq"])
                _, _, arr, meta = _transport.decode_tq_frame(fr)
                ack = _transport.encode_tq_ack(self._recv_ch, seq)
                with deadline_guard(
                        f"mpmd tq ack {self._recv_ch}", self._timeout):
                    self._chan.send(ack)  # best-effort; ack is cumulative
                return arr, (meta or {})
            if time.monotonic() > t_end:
                raise TimeoutError(
                    f"mpmd recv on {self._recv_ch!r} timed out after "
                    f"{limit:.0f}s — upstream stage dead or wedged")
            self._pump()
            time.sleep(0.0002)


def local_boundary(bid: int, *, wire: str = "raw"
                   ) -> Tuple[BoundaryEndpoint, BoundaryEndpoint]:
    """(upstream, downstream) endpoints over in-process queues. Boundary
    ``bid`` connects stage bid -> bid+1; activations ride ``act<bid>``,
    cotangents ``cot<bid>``."""
    up_chan, down_chan = _local_chan_pair()
    up = BoundaryEndpoint(up_chan, f"act{bid}", f"cot{bid}", wire=wire)
    down = BoundaryEndpoint(down_chan, f"cot{bid}", f"act{bid}", wire=wire)
    return up, down


def tcp_boundary(bid: int, *, wire: str = "raw"
                 ) -> Tuple[BoundaryEndpoint, BoundaryEndpoint]:
    """Same pair over a real loopback TCP connection (the multi-process
    wire path: frames cross the transport's length-prefixed codec, seq
    dedup and reconnect replay are live)."""
    server_chan = _ServerChan()
    down = BoundaryEndpoint(server_chan, f"cot{bid}", f"act{bid}", wire=wire)
    client_chan = _ClientChan(server_chan.addr, seed=bid)
    up = BoundaryEndpoint(client_chan, f"act{bid}", f"cot{bid}", wire=wire)
    return up, down


# ---------------------------------------------------------------------------
# Per-stage compiled programs
# ---------------------------------------------------------------------------
class MpmdStage:
    """One pipeline stage: a contiguous layer slice, a private mesh over a
    device subset, and lazily AOT-compiled programs.

    Programs (the stage program contract, docs/PIPELINE.md §MPMD):

    * ``fwd(params, bufs, x) -> y`` — non-last stages; batch dim sharded
      over this stage's ``dp`` axis, params/buffers replicated; buffers
      are non-differentiated inputs.
    * ``bwd(params, bufs, x, gy, acc) -> (dx, acc')`` —
      recompute-in-backward vjp of fwd; ``acc`` carries the running
      gradient sum so microbatch accumulation stays on-device.
    * ``loss_grad(params, head, bufs, x, acc, head_acc, 1/M[, y]) ->
      (loss_mb, dx, acc', head_acc')`` — last stage: forward through the
      head + loss, grads scaled by 1/M so summing cotangents over
      microbatches reproduces the full-batch mean loss.

    Compilation follows the engine's AOT idiom: lower once per
    (program, shapes), fingerprint with ``CompileCache.key_for(...,
    stage={id, layers, dp})`` and ``load_or_compile`` when the cache is
    enabled. The ``stage`` key part is what makes a resize stage-local:
    other stages' keys — and their on-disk entries — do not change.
    """

    def __init__(self, stage_id: int, apply_layer: Callable, positions:
                 Sequence[int], devices: Sequence, *, head_apply:
                 Optional[Callable] = None, loss_fn: Optional[Callable] =
                 None, cache=None, where: str = "mpmd"):
        self.stage_id = int(stage_id)
        self._apply_layer = apply_layer          # (leaf_vals, x) -> y
        self.positions = tuple(int(p) for p in positions)
        self._head_apply = head_apply            # (head_leaves, y) -> out
        self._loss_fn = loss_fn
        self._cache = cache
        self._where = where
        self.compile_count = 0
        self.cache_hits = 0
        self._programs: Dict[tuple, object] = {}
        self._set_devices(devices)

    # -- mesh / placement ---------------------------------------------------
    def _set_devices(self, devices: Sequence) -> None:
        devices = list(devices)
        if not devices:
            raise ValueError(f"stage {self.stage_id} got an empty "
                             "device subset")
        self.devices = devices
        self.dp = len(devices)
        self.mesh = Mesh(np.asarray(devices), ("dp",))
        self._repl = NamedSharding(self.mesh, P())
        self._batch = NamedSharding(self.mesh, P("dp"))

    def resize(self, devices: Sequence) -> None:
        """Stage-local width change: new mesh over a new device subset,
        THIS stage's programs dropped. Nothing else in the pipeline is
        touched — the acceptance gate asserts the other stages' compile
        counts and cache entries survive."""
        old = self.dp
        self._set_devices(devices)
        self._programs.clear()
        _obs.event("mpmd_stage_resize", stage=self.stage_id, old_dp=old,
                   new_dp=self.dp)

    def put_batch(self, arr) -> jax.Array:
        """Batch tensor onto this stage's mesh: dp-sharded along dim 0
        when divisible, replicated otherwise (a width that does not
        divide the microbatch rows cannot shard them — unequal-width
        stacks hit this on purpose)."""
        arr = np.asarray(arr)
        sh = self._batch if (arr.ndim and arr.shape[0] % self.dp == 0) \
            else self._repl
        return jax.device_put(arr, sh)

    def put_leaves(self, leaves):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(np.asarray(a), self._repl), leaves)

    # -- stage functions (traced) -------------------------------------------
    # ``params``/``bufs`` are tuples over this stage's layers of leaf
    # tuples; buffers ride as non-differentiated program inputs (an int
    # mask buffer must never meet jax.vjp).
    def _forward_only(self, params, bufs, x):
        with no_grad():
            h = x
            for lp, lb in zip(params, bufs):
                h = self._apply_layer(tuple(lp) + tuple(lb), h)
            return h

    def _forward_loss(self, params, head_leaves, bufs, x, *extra):
        with no_grad():
            h = x
            for lp, lb in zip(params, bufs):
                h = self._apply_layer(tuple(lp) + tuple(lb), h)
            if self._head_apply is not None:
                h = self._head_apply(head_leaves, h)
            if self._loss_fn is None:
                raise ValueError("last stage needs loss_fn")
            return self._loss_fn(h, *extra)

    # -- AOT build ----------------------------------------------------------
    def _build(self, kind: str, fn, example_args) -> object:
        key = None
        lowered = jax.jit(fn).lower(*example_args)
        if self._cache is not None:
            key = self._cache.key_for(
                lowered,
                config={"kind": kind},
                mesh=self.mesh,
                stage={"id": self.stage_id, "layers": list(self.positions),
                       "dp": self.dp},
            )
            compiled, hit = self._cache.load_or_compile(
                lowered, key, where=self._where)
            if hit:
                self.cache_hits += 1
            else:
                self.compile_count += 1
        else:
            compiled, hit = lowered.compile(), False
            self.compile_count += 1
        _obs.inc("mpmd_stage_compile_total", stage=self.stage_id,
                 program=kind, hit=str(hit).lower())
        return compiled

    def cache_key(self, kind: str, fn, example_args) -> Optional[str]:
        """The compile-cache key this stage would use for ``kind`` (test
        hook for the resize gate: unresized stages' keys must not move)."""
        if self._cache is None:
            return None
        lowered = jax.jit(fn).lower(*example_args)
        return self._cache.key_for(
            lowered, config={"kind": kind}, mesh=self.mesh,
            stage={"id": self.stage_id, "layers": list(self.positions),
                   "dp": self.dp})

    def _program(self, kind: str, shapes: tuple, builder) -> object:
        key = (kind, shapes, self.dp)
        prog = self._programs.get(key)
        if prog is None:
            prog = builder()
            self._programs[key] = prog
        return prog

    # -- public ops (device_put'd args -> committed outputs) ----------------
    def fwd(self, params, bufs, x):
        prog = self._program(
            "fwd", (tuple(np.shape(x)),),
            lambda: self._build("fwd", self._forward_only,
                                (params, bufs, x)))
        return prog(params, bufs, x)

    def bwd(self, params, bufs, x, gy, acc):
        def fn(pv, bv, xv, g, ac):
            _, pull = jax.vjp(
                lambda p_, x_: self._forward_only(p_, bv, x_), pv, xv)
            dl, dx = pull(g)
            return dx, jax.tree_util.tree_map(jnp.add, ac, dl)

        prog = self._program(
            "bwd", (tuple(np.shape(x)), tuple(np.shape(gy))),
            lambda: self._build("bwd", fn, (params, bufs, x, gy, acc)))
        return prog(params, bufs, x, gy, acc)

    def loss_grad(self, params, head_leaves, bufs, x, acc, head_acc,
                  inv_m, *extra):
        def fn(pv, hv, bv, xv, ac, hac, inv, *ex):
            loss, (dl, dh, dx) = jax.value_and_grad(
                self._forward_loss, argnums=(0, 1, 3))(pv, hv, bv, xv, *ex)
            scale = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda g: g * inv, t)
            return (loss,
                    dx * inv,
                    jax.tree_util.tree_map(jnp.add, ac, scale(dl)),
                    jax.tree_util.tree_map(jnp.add, hac, scale(dh)))

        prog = self._program(
            "loss_grad",
            (tuple(np.shape(x)), tuple(tuple(np.shape(e)) for e in extra)),
            lambda: self._build(
                "loss_grad", fn,
                (params, head_leaves, bufs, x, acc, head_acc, inv_m)
                + extra))
        return prog(params, head_leaves, bufs, x, acc, head_acc, inv_m,
                    *extra)


# ---------------------------------------------------------------------------
# The pipeline driver
# ---------------------------------------------------------------------------
def _partition(n_items: int, n_parts: int) -> List[Tuple[int, int]]:
    """Contiguous [lo, hi) slices, remainder spread from the front."""
    base, rem = divmod(n_items, n_parts)
    out, lo = [], 0
    for i in range(n_parts):
        hi = lo + base + (1 if i < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


class MpmdPipeline:
    """MPMD driver over an existing ``SpmdPipeline``'s parameters.

    Construction does NOT copy or re-own parameters: the stacked
    parameters of the SpmdPipeline (plus an optional head layer) stay the
    single source of truth, so the caller's optimizer — the exact object
    the SPMD path trains with — updates the same state. ``train_batch``
    computes the full pipeline loss MPMD-style (per-stage programs, async
    boundary queues) and leaves the accumulated gradients on ``p.grad``,
    mirroring ``loss.backward()``; the caller runs ``opt.step()`` as
    usual. That is what makes the SPMD-vs-MPMD trajectory gate a
    three-line test.

    ``widths`` picks each stage's dp independently (unequal allowed);
    device subsets are consecutive slices of ``jax.devices()``. V>1
    (interleaved virtual stages) stays SPMD-only — MPMD boundaries are
    per physical stage.
    """

    def __init__(self, spmd, widths: Optional[Sequence[int]] = None, *,
                 head=None, loss_fn: Optional[Callable] = None,
                 num_microbatches: Optional[int] = None,
                 schedule: str = "1f1b", transport: str = "local",
                 wire: Optional[str] = None, devices=None,
                 cache_dir: Optional[str] = None,
                 shard_dir: Optional[str] = None,
                 layer_split: Optional[Sequence[int]] = None):
        from .fleet.meta_parallel.pipeline_parallel import (
            PP_SCHEDULES, phased_stage_table)

        if getattr(spmd, "num_virtual_stages", 1) != 1:
            raise ValueError("MPMD supports V=1 only; interleaved virtual "
                             "stages stay on the SPMD path")
        if schedule not in PP_SCHEDULES:
            raise ValueError(f"schedule={schedule!r} not in {PP_SCHEDULES}")
        widths = list(widths or parse_stage_widths() or
                      [1] * max(spmd.num_stages, 1))
        self._spmd = spmd
        self._table_fn = phased_stage_table
        self.schedule = schedule
        self.num_stages = len(widths)
        self.num_microbatches = int(num_microbatches or
                                    getattr(spmd, "num_microbatches", None)
                                    or 4)
        self.wire = resolve_wire(wire)
        self.transport = transport
        self.head = head
        self._loss_fn = loss_fn or (lambda y, *e: (y ** 2).mean())
        self.step_index = 0
        self.shard_dir = shard_dir
        self.last_step_stats: Dict[int, Dict[str, float]] = {}
        cache = _resolve_cache(cache_dir)

        devices = list(devices if devices is not None else jax.devices())
        need = sum(widths)
        if need > len(devices):
            raise ValueError(f"stage widths {widths} need {need} devices, "
                             f"have {len(devices)}")
        # layer slice + device slice per stage
        L = spmd.num_layers
        order = list(getattr(spmd, "_layer_order", range(L)))
        if layer_split is not None:
            # explicit per-stage layer COUNTS — an unbalanced stack puts
            # more layers on one stage and compensates with its width
            sizes = [int(n) for n in layer_split]
            if (len(sizes) != self.num_stages or any(n < 1 for n in sizes)
                    or sum(sizes) != L):
                raise ValueError(
                    f"layer_split={list(layer_split)} must be "
                    f"{self.num_stages} positive counts summing to {L}")
            self._slices, lo = [], 0
            for n in sizes:
                self._slices.append((lo, lo + n))
                lo += n
        else:
            self._slices = _partition(L, self.num_stages)
        head_params = ([p for _, p in head.named_parameters()]
                       if head is not None else [])
        self._head_params = head_params

        # stage functional forms reuse the SPMD template-rebind apply
        apply_layer = spmd._apply_block

        def head_apply(head_leaves, y):
            originals = [p._value for p in head_params]
            try:
                for p, v in zip(head_params, head_leaves):
                    p._value = v
                return raw(head(Tensor(y)))
            finally:
                for p, v in zip(head_params, originals):
                    p._value = v

        self.stages: List[MpmdStage] = []
        dev_lo = 0
        for s, ((lo, hi), dp) in enumerate(zip(self._slices, widths)):
            last = s == self.num_stages - 1
            self.stages.append(MpmdStage(
                s, apply_layer, [order.index(l) for l in range(lo, hi)],
                devices[dev_lo:dev_lo + dp],
                head_apply=head_apply if (last and head is not None)
                else None,
                loss_fn=self._loss_fn if last else None,
                cache=cache))
            dev_lo += dp
        self._build_boundaries()

    # -- boundaries ---------------------------------------------------------
    def _build_boundaries(self) -> None:
        make = tcp_boundary if self.transport == "tcp" else local_boundary
        self._up: List[BoundaryEndpoint] = []     # owned by stage i
        self._down: List[BoundaryEndpoint] = []   # owned by stage i+1
        for i in range(self.num_stages - 1):
            up, down = make(i, wire=self.wire)
            self._up.append(up)
            self._down.append(down)

    # -- parameter plumbing -------------------------------------------------
    def parameters(self):
        ps = list(self._spmd.parameters())
        if self.head is not None:
            ps += list(self.head.parameters())
        return ps

    def _stage_leaves(self, s: int):
        """(params, bufs) per-layer leaf tuples for stage s, sliced out of
        the stacked leaves at that stage's stacked positions."""
        stage = self.stages[s]
        p_stk = [np.asarray(raw(p)) for p in self._spmd._stacked]
        b_stk = [np.asarray(raw(b)) for b in self._spmd._stacked_bufs]
        params = tuple(tuple(leaf[pos] for leaf in p_stk)
                       for pos in stage.positions)
        bufs = tuple(tuple(leaf[pos] for leaf in b_stk)
                     for pos in stage.positions)
        return params, bufs

    def compile_counts(self) -> Dict[int, int]:
        return {s.stage_id: s.compile_count for s in self.stages}

    def resize_stage(self, s: int, dp: int, devices=None) -> None:
        """Change ONE stage's width. Device subsets are re-derived only if
        the caller does not pin them; every other stage keeps its mesh,
        its compiled programs and its compile-cache entries."""
        if devices is None:
            # reuse the stage's current leading device, extend from the
            # global pool avoiding other stages' devices
            taken = {id(d) for st in self.stages if st.stage_id != s
                     for d in st.devices}
            pool = [d for d in jax.devices() if id(d) not in taken]
            devices = pool[:dp]
        if len(devices) < dp:
            raise ValueError(f"resize_stage({s}, dp={dp}): only "
                             f"{len(devices)} free devices")
        self.stages[s].resize(devices[:dp])

    # -- one training step --------------------------------------------------
    def train_batch(self, x, y=None) -> float:
        """Forward+backward over all microbatches via the per-stage
        programs; accumulated grads land on ``p.grad`` (like
        ``loss.backward()``), loss returned as a float."""
        t_step = time.perf_counter()
        M, S = self.num_microbatches, self.num_stages
        xv = np.asarray(raw(x) if isinstance(x, Tensor) else x)
        if xv.shape[0] % M:
            raise ValueError(f"batch {xv.shape[0]} not divisible by "
                             f"M={M} microbatches")
        mbs = np.split(xv, M)
        ymbs = None
        if y is not None:
            yv = np.asarray(raw(y) if isinstance(y, Tensor) else y)
            ymbs = np.split(yv, M)

        table = self._table_fn(S, 1, M, self.schedule)
        # leaves + grad accumulators, committed per stage (main thread:
        # template-rebind tracing is not thread-safe, so every program is
        # also built here before the runners start)
        params, bufs = [], []
        for st in self.stages:
            p, b = self._stage_leaves(st.stage_id)
            params.append(st.put_leaves(p))
            bufs.append(st.put_leaves(b))
        accs = [jax.tree_util.tree_map(jnp.zeros_like, pv) for pv in params]
        last = self.stages[-1]
        head_leaves = last.put_leaves(
            tuple(np.asarray(raw(p)) for p in self._head_params))
        head_acc = jax.tree_util.tree_map(jnp.zeros_like, head_leaves)
        inv_m = jax.device_put(np.float32(1.0 / M), last._repl)
        self._precompile(params, bufs, head_leaves, accs, head_acc, inv_m,
                         mbs[0], ymbs[0] if ymbs else ())

        losses: Dict[int, object] = {}
        out_accs: List[object] = list(accs)
        out_head = [head_acc]
        errors: List[BaseException] = []
        with _obs.span("mpmd_step", step=self.step_index, stages=S,
                       microbatches=M, schedule=self.schedule,
                       transport=self.transport, wire=self.wire):
            threads = [
                threading.Thread(
                    target=self._run_stage,
                    args=(s, table[s], mbs, ymbs, params, bufs,
                          head_leaves, inv_m, out_accs, out_head, losses,
                          errors),
                    name=f"mpmd-stage-{s}", daemon=True)
                for s in range(S)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=_QUEUE_TIMEOUT * 2)
            if any(t.is_alive() for t in threads):
                raise TimeoutError("mpmd stage runner wedged")
            if errors:
                raise errors[0]
        self._scatter_grads(out_accs, out_head[0])
        loss = float(sum(np.asarray(losses[m]) for m in range(M)) / M)
        self.step_index += 1
        _obs.observe("mpmd_step_seconds", time.perf_counter() - t_step)
        self.export_stage_stats()
        if self.shard_dir:
            self.save_shards(self.shard_dir)
        return loss

    def _precompile(self, params, bufs, head_leaves, accs, head_acc,
                    inv_m, x0, extra) -> None:
        if isinstance(extra, (tuple, list)):
            extra = tuple(extra)
        else:
            extra = (extra,)
        probe = np.asarray(x0)
        for s, st in enumerate(self.stages):
            x_d = st.put_batch(np.zeros_like(probe))
            if s == self.num_stages - 1:
                ex = tuple(st.put_batch(e) for e in extra)
                st.loss_grad(params[s], head_leaves, bufs[s], x_d,
                             accs[s], head_acc, inv_m, *ex)
                break
            y_aval = jax.eval_shape(st._forward_only, params[s], bufs[s],
                                    x_d)
            st.fwd(params[s], bufs[s], x_d)
            g_d = st.put_batch(np.zeros(y_aval.shape, y_aval.dtype))
            # NB: bwd consumes gy shaped like THIS stage's output
            st.bwd(params[s], bufs[s], x_d, g_d, accs[s])
            probe = np.zeros(y_aval.shape, y_aval.dtype)

    def _run_stage(self, s, ops, mbs, ymbs, params, bufs, head_leaves,
                   inv_m, out_accs, out_head, losses, errors) -> None:
        try:
            st = self.stages[s]
            last = s == self.num_stages - 1
            up = self._up[s] if s < self.num_stages - 1 else None
            down = self._down[s - 1] if s > 0 else None
            acc = out_accs[s]
            head_acc = out_head[0]
            stash: Dict[int, object] = {}
            busy = 0.0
            t0 = time.perf_counter()
            for op_i, (tick, kind, mb, _k) in enumerate(ops):
                chaos.mpmd_fence(s, op_i)
                if kind == "F":
                    if s == 0:
                        x_mb = st.put_batch(mbs[mb])
                    else:
                        arr, meta = down.recv()
                        if meta.get("mb") != mb:
                            raise RuntimeError(
                                f"stage {s} expected act mb={mb}, got "
                                f"{meta.get('mb')} — schedule skew")
                        x_mb = st.put_batch(arr)
                    stash[mb] = x_mb
                    if not last:
                        # block inside the busy window: dispatch is async,
                        # so timing the call alone would book the compute
                        # as idle (the very next np.asarray forces it
                        # anyway — this only moves WHERE it is counted)
                        t1 = time.perf_counter()
                        y_mb = jax.block_until_ready(
                            st.fwd(params[s], bufs[s], x_mb))
                        busy += time.perf_counter() - t1
                        up.send(np.asarray(y_mb), mb=mb)
                else:  # "B"
                    x_mb = stash.pop(mb)
                    if last:
                        ex = ((st.put_batch(ymbs[mb]),) if ymbs is not None
                              else ())
                        t1 = time.perf_counter()
                        loss_mb, dx, acc, head_acc = jax.block_until_ready(
                            st.loss_grad(
                                params[s], head_leaves, bufs[s], x_mb, acc,
                                head_acc, inv_m, *ex))
                        busy += time.perf_counter() - t1
                        losses[mb] = loss_mb
                    else:
                        g_arr, meta = up.recv()
                        if meta.get("mb") != mb:
                            raise RuntimeError(
                                f"stage {s} expected cot mb={mb}, got "
                                f"{meta.get('mb')} — schedule skew")
                        gy = st.put_batch(g_arr)
                        t1 = time.perf_counter()
                        dx, acc = jax.block_until_ready(
                            st.bwd(params[s], bufs[s], x_mb, gy, acc))
                        busy += time.perf_counter() - t1
                    if s > 0:
                        down.send(np.asarray(dx), mb=mb)
                _obs.inc("mpmd_tick_total", stage=s, kind=kind)
            out_accs[s] = acc
            if last:
                out_head[0] = head_acc
            wall = max(time.perf_counter() - t0, 1e-9)
            idle = max(0.0, 1.0 - busy / wall)
            self.last_step_stats[s] = {"busy_s": busy, "wall_s": wall,
                                       "idle_fraction": idle}
            _obs.set_gauge("mpmd_stage_idle_fraction", idle, stage=s)
        except BaseException as exc:  # noqa: BLE001 — surfaced to driver
            errors.append(exc)

    def export_stage_stats(self) -> Dict[str, Dict[str, float]]:
        """Publish the last step's per-stage busy/idle stats to the live
        telemetry plane (observability/live.py) so the fleet aggregator
        can watch stage imbalance while the job runs. The gauges already
        export the same numbers post-hoc; this is the streaming hook.
        One env lookup when the live plane is off. Returns the exported
        mapping (stage id -> stats) for callers that want it."""
        stats = {str(s): rec for s, rec in self.last_step_stats.items()}
        from ..observability import live as _live

        _live.note_stage_stats(stats)
        return stats

    # -- grads back onto the shared parameters ------------------------------
    def _scatter_grads(self, out_accs, head_acc) -> None:
        n_params = len(self._spmd._stacked)
        stacked_grads = [np.zeros(np.shape(raw(p)), np.asarray(raw(p)).dtype)
                         for p in self._spmd._stacked]
        for st, acc in zip(self.stages, out_accs):
            for layer_i, pos in enumerate(st.positions):
                layer_grads = acc[layer_i]
                for leaf_i in range(n_params):
                    stacked_grads[leaf_i][pos] = np.asarray(
                        layer_grads[leaf_i])
        for p, g in zip(self._spmd._stacked, stacked_grads):
            p.grad = g
        for p, g in zip(self._head_params, head_acc):
            p.grad = np.asarray(g)

    # -- per-stage checkpoint shards ----------------------------------------
    def save_shards(self, base_dir: str, optimizer=None) -> None:
        """Each stage commits its own shard: its layers' slices of the
        stacked params (+opt accumulator leaves when given), the head
        riding in the last stage's shard."""
        from .fleet.elastic import save_stage_shard

        acc_by_pid = {}
        if optimizer is not None:
            for i, p in enumerate(optimizer._parameter_list):
                acc_by_pid[id(p)] = (optimizer, i)
        for st in self.stages:
            state: Dict[str, np.ndarray] = {}
            pos = list(st.positions)
            for pi, p in enumerate(self._spmd._stacked):
                v = np.asarray(raw(p))
                state[f"p{pi}"] = v[pos]
                state.update(self._opt_slices(acc_by_pid.get(id(p)),
                                              f"p{pi}", pos, v.shape[0]))
            if st.stage_id == self.num_stages - 1:
                for hi, p in enumerate(self._head_params):
                    state[f"h{hi}"] = np.asarray(raw(p))
                    state.update(self._opt_slices(
                        acc_by_pid.get(id(p)), f"h{hi}", None, -1))
            save_stage_shard(base_dir, st.stage_id, self.step_index, state)

    @staticmethod
    def _opt_slices(ref, prefix, pos, stacked_len) -> Dict[str, np.ndarray]:
        if ref is None:
            return {}
        opt, i = ref
        st = opt._accumulators[i]
        if not st:
            return {}
        out = {}
        for k, v in st.items():
            v = np.asarray(v)
            if pos is not None and v.ndim >= 1 and v.shape[0] == stacked_len:
                out[f"{prefix}.opt.{k}"] = v[pos]
            else:
                out[f"{prefix}.opt.{k}"] = v
        return out

    def restore_shards(self, base_dir: str, optimizer=None
                       ) -> Optional[int]:
        """Rebind params (and opt accumulators) from the newest step every
        stage committed; queue cursors restart clean because a restored
        step replays from its first microbatch. Returns the restored step
        or None (nothing committed)."""
        from .fleet.elastic import latest_common_step, load_stage_shard

        step = latest_common_step(base_dir, self.num_stages)
        if step is None:
            return None
        shards = [load_stage_shard(base_dir, s, step)
                  for s in range(self.num_stages)]
        for pi, p in enumerate(self._spmd._stacked):
            full = np.asarray(raw(p)).copy()
            opt_full: Dict[str, np.ndarray] = {}
            for st, shard in zip(self.stages, shards):
                pos = list(st.positions)
                full[pos] = np.asarray(shard[f"p{pi}"])
                for k, v in shard.items():
                    if k.startswith(f"p{pi}.opt."):
                        name = k.split(".opt.", 1)[1]
                        v = np.asarray(v)
                        if v.ndim >= 1 and v.shape[0] == len(pos):
                            tgt = opt_full.setdefault(
                                name, np.zeros(full.shape, v.dtype)
                                if v.shape[1:] == full.shape[1:] else v)
                            if tgt.shape == full.shape:
                                tgt[pos] = v
                        else:
                            opt_full[name] = v
            p._rebind(Tensor(jnp.asarray(full)))
            self._load_opt(optimizer, p, opt_full)
        last_shard = shards[-1]
        for hi, p in enumerate(self._head_params):
            p._rebind(Tensor(jnp.asarray(np.asarray(last_shard[f"h{hi}"]))))
            opt_full = {k.split(".opt.", 1)[1]: np.asarray(v)
                        for k, v in last_shard.items()
                        if k.startswith(f"h{hi}.opt.")}
            self._load_opt(optimizer, p, opt_full)
        self.step_index = step
        return step

    @staticmethod
    def _load_opt(optimizer, p, leaves: Dict[str, np.ndarray]) -> None:
        if optimizer is None or not leaves:
            return
        for i, q in enumerate(optimizer._parameter_list):
            if q is p:
                st = dict(optimizer._accumulators[i] or {})
                for k, v in leaves.items():
                    # keep 0-dim leaves as f32 arrays: .item() would promote
                    # beta*_pow to a python f64 and the bias-correction chain
                    # would round differently than an unrestored run
                    st[k] = jnp.asarray(v)
                optimizer._accumulators[i] = st
                return
