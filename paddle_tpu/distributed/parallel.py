"""paddle.DataParallel + spawn parity.

Reference (SURVEY.md §2.3 "Data parallel, dygraph"): `paddle.DataParallel`
wraps a Layer with the C++ EagerReducer — bucketed, overlapped grad
allreduce, `no_sync`, find_unused_parameters (`imperative/reducer.cc`).

TPU-native design: under SPMD with a compiled train step, data-parallel grad
reduction is emitted by XLA from the batch sharding — there is nothing to
bucket or overlap by hand (the latency-hiding scheduler does it). The
wrapper's job reduces to (a) placing the module's params on the mesh and
(b) keeping the API (`no_sync`, `scale_loss`) alive for ported scripts.
"""
from __future__ import annotations

import contextlib
import multiprocessing
import os
from typing import Optional

from ..nn.layer import Layer
from .env import get_world_size, init_parallel_env


class DataParallel(Layer):
    def __init__(
        self,
        layers: Layer,
        strategy=None,
        comm_buffer_size: int = 25,
        last_comm_buffer_size: int = 1,
        find_unused_parameters: bool = False,
        group=None,
    ):
        super().__init__()
        init_parallel_env()
        self._layers = layers
        from .fleet import shard_model_parameters

        shard_model_parameters(layers)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        # grad sync is part of the compiled step; nothing to defer
        yield

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)


def spawn(func, args=(), nprocs: Optional[int] = None, join=True, daemon=False, **options):
    """paddle.distributed.spawn parity.

    On TPU the unit of multi-process execution is one process per *host* (JAX
    single-controller owns every local chip), so in-host spawn degenerates to
    a direct call; multi-host launch goes through `paddle_tpu.distributed.launch`.
    """
    if nprocs in (None, 1) or get_world_size() >= 1:
        func(*args)
        return None
    procs = []
    ctx = multiprocessing.get_context("spawn")
    for rank in range(nprocs):
        env = dict(os.environ, PADDLE_TRAINER_ID=str(rank), PADDLE_TRAINERS_NUM=str(nprocs))
        p = ctx.Process(target=_spawn_target, args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs


def _spawn_target(func, args, env):
    os.environ.update(env)
    func(*args)
