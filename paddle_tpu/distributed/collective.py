"""Collective communication API (paddle.distributed.* parity).

Reference capability (SURVEY.md §2.3 "Collective ops", "Comm APIs"): per-op
NCCL collectives — `c_allreduce_sum`, `c_allgather`, `c_broadcast`,
`c_reducescatter`, `alltoall`, `send_v2/recv_v2` — issued eagerly on comm
streams (`paddle/fluid/operators/collective/`, ProcessGroupNCCL).

TPU-native design — two execution contexts, one API:

* **Traced** (inside `shard_map`/`pjit`-traced code, where values carry named
  mesh axes): each call lowers to the XLA collective — `lax.psum`,
  `lax.all_gather`, `lax.psum_scatter`, `lax.all_to_all`, `lax.ppermute` —
  scheduled by XLA over ICI/DCN. This is the hot path; it is how the parallel
  layers and pipeline schedules are built.

* **Eager** (plain arrays under the single-controller SPMD runtime): there is
  no per-rank divergent state — an array is *global*, possibly sharded over
  mesh devices. Eager collectives are therefore *reshardings / global
  reductions of the global view*, with per-rank semantics derived from the
  convention that each device holds equal (replicated) or sharded slices:
  all_reduce(SUM) on a replicated array multiplies by nranks (every rank
  contributed an equal tensor); all_gather stacks the per-device view;
  reduce_scatter shards; broadcast is the identity (global arrays are already
  consistent). These match what the NCCL ops would produce rank-by-rank under
  the same data placement.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..framework.core import Tensor, is_tracer_value
from ..framework.op import raw
from .env import Group, _resolve_group


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _axes(group: Group):
    names = group.axis_names
    return names[0] if len(names) == 1 else names


def _in_trace(v) -> bool:
    return is_tracer_value(v)


def _wrap_like(x, out):
    return Tensor(out) if isinstance(x, Tensor) else out


def _promote_subf32_reduce(dt) -> bool:
    """True when a sub-f32 sum-reduce must run in f32: ONLY on the CPU
    backend, whose AllReducePromotion pass CHECK-fails cloning the
    copy-rooted reduction region jax emits for bf16 psums
    (``hlo_instruction.cc`` "Invalid binary instruction opcode copy",
    jaxlib 0.9) — SIGABRTing compilation of bf16 pipeline schedules on
    emulated meshes. On TPU the native-dtype reduce is kept: promoting
    there would double collective wire bytes on the gradient hot path."""
    if dt not in (jnp.bfloat16, jnp.float16):
        return False
    try:
        return jax.default_backend() == "cpu"
    except Exception:
        return True


def psum_f32safe(v, ax):
    """``lax.psum`` with sub-f32 floats promoted to f32 for the reduce
    where required (see :func:`_promote_subf32_reduce`)."""
    dt = v.dtype
    if _promote_subf32_reduce(dt):
        return lax.psum(v.astype(jnp.float32), ax).astype(dt)
    return lax.psum(v, ax)


def pmean_f32safe(v, ax):
    """``lax.pmean`` through the same promotion (pmean lowers to
    psum / axis-size, hitting the same XLA CPU pass)."""
    dt = v.dtype
    if _promote_subf32_reduce(dt):
        return lax.pmean(v.astype(jnp.float32), ax).astype(dt)
    return lax.pmean(v, ax)


def psum_scatter_f32safe(v, ax, scatter_dimension=0, tiled=True):
    """``lax.psum_scatter`` through the same promotion (same pass, same
    copy-rooted bf16 reduction region, confirmed same SIGABRT)."""
    dt = v.dtype
    if _promote_subf32_reduce(dt):
        return lax.psum_scatter(
            v.astype(jnp.float32), ax, scatter_dimension=scatter_dimension,
            tiled=tiled).astype(dt)
    return lax.psum_scatter(v, ax, scatter_dimension=scatter_dimension,
                            tiled=tiled)


def psum_quantized(v, ax, wire_dtype="bf16", via="simulate"):
    """Reduced-precision all-reduce: each contributor's value passes
    through the wire dtype (bf16 round-trip, or int8 with a per-call
    absmax scale) and the accumulation runs in f32.

    ``via="simulate"`` (the historical default) quantize-round-trips the
    contribution but still moves f32 bytes in the compiled HLO — the
    numerics of a reduced wire without the bytes. ``via="gather"``
    exchanges the REAL reduced payload: each shard's int8/bf16 value plus
    its f32 scale is all-gathered at wire dtype and the sum runs in f32
    after dequant, so ``comm_analysis`` sees s8/bf16 collective operands.
    ``distributed.grad_comm`` (dp gradient buckets, simulate) and
    ``distributed.mp_comm`` (mp activation wire, gather) are the
    production callers; exposed here as the single audited primitive for
    tests and benches."""
    from .grad_comm import quantize_roundtrip

    if via == "gather":
        return _psum_gather_wire(v, ax, wire_dtype)
    q = quantize_roundtrip(v.astype(jnp.float32), wire_dtype)
    return lax.psum(q, ax).astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _psum_gather_wire(v, ax, wire_dtype):
    """psum through a real reduced-precision exchange: stack-gather the
    wire payload (+ per-shard absmax scale for int8), dequantize, sum in
    f32. Backward is the straight-through psum of the wire-round-tripped
    cotangent — symmetric with the forward wire."""
    from .grad_comm import quantize_absmax

    dt = v.dtype
    v32 = v.astype(jnp.float32)
    if wire_dtype == "int8":
        q, scale = quantize_absmax(v32)
        gq = lax.all_gather(q, ax, axis=0, tiled=False).astype(jnp.float32)
        gs = lax.all_gather(scale, ax, axis=0, tiled=False)
        return jnp.sum(gq * gs, axis=0).astype(dt)
    if wire_dtype == "bf16":
        g = lax.all_gather(v32.astype(jnp.bfloat16), ax, axis=0,
                           tiled=False).astype(jnp.float32)
        return jnp.sum(g, axis=0).astype(dt)
    return lax.psum(v, ax)


def _psum_gather_wire_fwd(v, ax, wire_dtype):
    return _psum_gather_wire(v, ax, wire_dtype), None


def _psum_gather_wire_bwd(ax, wire_dtype, _res, ct):
    from .grad_comm import quantize_roundtrip

    ctq = quantize_roundtrip(ct.astype(jnp.float32), wire_dtype)
    return (psum_f32safe(ctq, ax).astype(ct.dtype),)


_psum_gather_wire.defvjp(_psum_gather_wire_fwd, _psum_gather_wire_bwd)


def all_gather_quantized(v, ax, *, wire_dtype="int8",
                         segments: Optional[Tuple[int, ...]] = None,
                         grad_wire: Optional[str] = None):
    """All-gather a flat f32 vector through a reduced-precision wire.

    int8: the shard payload crosses the mesh as s8 with per-segment f32
    absmax scales (``segments`` are the flat element counts of the leaves
    packed into ``v`` — one scale per leaf; one global scale when
    omitted); bf16: a plain bf16 gather. Dequantization and all
    downstream math run in f32. The backward transposes to a
    ``psum_scatter`` of the wire-round-tripped cotangent (``grad_wire``,
    defaulting to the forward wire) — the quantized-symmetric cotangent
    collective. Contract: ``v`` is 1-D and gathers tiled on axis 0,
    matching the packed-leaf layout of ``grad_comm.gather_leaves``."""
    if wire_dtype not in ("bf16", "int8"):
        return lax.all_gather(v, ax, axis=0, tiled=True)
    segs: Tuple[int, ...]
    if segments is None:
        segs = (int(v.shape[0]),)
    else:
        segs = tuple(int(s) for s in segments)
        if sum(segs) != int(v.shape[0]):
            raise ValueError(
                f"all_gather_quantized: segments sum {sum(segs)} != "
                f"payload length {int(v.shape[0])}")
    return _agq(v, ax, wire_dtype, segs, grad_wire or wire_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _agq(v, ax, wire_dtype, segs, grad_wire):
    v32 = v.astype(jnp.float32)
    if wire_dtype == "bf16":
        return lax.all_gather(v32.astype(jnp.bfloat16), ax, axis=0,
                              tiled=True).astype(jnp.float32)
    from .grad_comm import _INT8_LEVELS

    offs = np.concatenate([[0], np.cumsum(segs)]).astype(np.int64)
    qs, scales = [], []
    for i, n in enumerate(segs):
        seg = v32[int(offs[i]):int(offs[i]) + int(n)]
        s = jnp.maximum(jnp.max(jnp.abs(seg)) / _INT8_LEVELS, 1e-12)
        qs.append(jnp.clip(jnp.round(seg / s), -_INT8_LEVELS,
                           _INT8_LEVELS).astype(jnp.int8))
        scales.append(s)
    q = jnp.concatenate(qs)
    svec = jnp.stack(scales)
    gq = lax.all_gather(q, ax, axis=0, tiled=True)
    gs = lax.all_gather(svec, ax, axis=0, tiled=False)  # [n_shards, n_segs]
    n_total = int(sum(segs))
    blocks = gq.reshape((-1, n_total)).astype(jnp.float32)
    sexp = jnp.repeat(gs, repeats=np.asarray(segs), axis=1,
                      total_repeat_length=n_total)
    return (blocks * sexp).reshape(-1)


def _agq_fwd(v, ax, wire_dtype, segs, grad_wire):
    return _agq(v, ax, wire_dtype, segs, grad_wire), None


def _agq_bwd(ax, wire_dtype, segs, grad_wire, _res, ct):
    from .grad_comm import quantize_roundtrip

    ctq = quantize_roundtrip(ct.astype(jnp.float32), grad_wire)
    return (lax.psum_scatter(ctq, ax, scatter_dimension=0, tiled=True),)


_agq.defvjp(_agq_fwd, _agq_bwd)


# ---------------------------------------------------------------- all_reduce
def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None, sync_op=True):
    g = _resolve_group(group)
    v = raw(tensor)
    if _in_trace(v):
        ax = _axes(g)
        if op == ReduceOp.SUM:
            out = psum_f32safe(v, ax)
        elif op == ReduceOp.MAX:
            out = lax.pmax(v, ax)
        elif op == ReduceOp.MIN:
            out = lax.pmin(v, ax)
        elif op == ReduceOp.AVG:
            out = pmean_f32safe(v, ax)
        else:
            # PROD: gather shards and multiply directly. The log-sum-exp
            # trick is NaN-gradient at v=0 and numerically poor; PROD
            # reduces are rare enough that the all-gather bandwidth is the
            # right trade (round-1 verdict, weak #7).
            gathered = lax.all_gather(v, ax)
            out = jnp.prod(gathered, axis=0)
    else:
        n = g.nranks
        if op == ReduceOp.SUM:
            out = v * n
        elif op == ReduceOp.AVG:
            out = v
        elif op in (ReduceOp.MAX, ReduceOp.MIN):
            out = v
        else:
            out = v**n
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # On TPU a rooted reduce is an all_reduce (result is consistent globally).
    return all_reduce(tensor, op=op, group=group, sync_op=sync_op)


# ---------------------------------------------------------------- all_gather
def all_gather(tensor_list: Optional[List], tensor=None, group=None, sync_op=True, axis=0):
    """paddle.distributed.all_gather parity.

    Also usable functionally: `out = all_gather(None, x, group)` returns the
    stacked [nranks, ...] result (traced) / list (eager).
    """
    g = _resolve_group(group if not isinstance(tensor_list, Group) else tensor_list)
    v = raw(tensor)
    if _in_trace(v):
        out = lax.all_gather(v, _axes(g), axis=0, tiled=False)
        if tensor_list is not None and isinstance(tensor_list, list):
            for i in range(g.nranks):
                tensor_list.append(_wrap_like(tensor, out[i]))
            return tensor_list
        return _wrap_like(tensor, out)
    # eager: every device holds the replicated global value
    outs = [_wrap_like(tensor, jnp.asarray(v)) for _ in range(g.nranks)]
    if tensor_list is not None and isinstance(tensor_list, list):
        tensor_list.extend(outs)
        return tensor_list
    return outs


def all_gather_object(object_list, obj, group=None):
    g = _resolve_group(group)
    object_list.extend([obj] * g.nranks)
    return object_list


# ----------------------------------------------------------------- broadcast
def broadcast(tensor, src=0, group=None, sync_op=True):
    g = _resolve_group(group)
    v = raw(tensor)
    if _in_trace(v):
        # select rank src's value on all ranks of the group axis
        ax = _axes(g)
        src_local = g.get_group_rank(src) if src in g.ranks else src
        gathered = lax.all_gather(v, ax, axis=0, tiled=False)
        out = gathered[src_local]
    else:
        out = jnp.asarray(v)  # global arrays are already consistent
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


# ------------------------------------------------------------- reduce_scatter
def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce across the group then scatter shards along dim 0.

    Functional traced form: `out = reduce_scatter(x, group=g)` with x of
    shape [n*k, ...] returns this rank's [k, ...] reduced shard.
    """
    g = _resolve_group(group)
    if tensor_list is not None:
        v = jnp.concatenate([raw(t) for t in tensor_list], axis=0)
    else:
        v = raw(tensor)
    if _in_trace(v):
        out = psum_scatter_f32safe(v, _axes(g), scatter_dimension=0, tiled=True)
    else:
        n = g.nranks
        idx = max(g.rank, 0)
        shard = v.shape[0] // n
        out = v[idx * shard : (idx + 1) * shard] * n
    if tensor_list is not None and isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return _wrap_like(tensor, out)


# -------------------------------------------------------------------- scatter
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _resolve_group(group)
    if tensor_list is not None:
        v = jnp.stack([raw(t) for t in tensor_list], axis=0)
    else:
        v = raw(tensor)
    if _in_trace(v):
        ax = _axes(g)
        idx = lax.axis_index(ax)
        out = lax.dynamic_index_in_dim(v, idx, axis=0, keepdims=False)
        out = broadcast(out, src=src, group=g)
        out = raw(out)
    else:
        idx = max(g.rank, 0)
        out = v[idx]
    if isinstance(tensor, Tensor):
        tensor._rebind(out)
        return tensor
    return out


# ------------------------------------------------------------------- alltoall
def alltoall(out_tensor_list, in_tensor_list=None, group=None, sync_op=True):
    """paddle.distributed.alltoall parity.

    Traced functional form: `out = alltoall(x, group=g)` where x's dim 0 is
    [nranks * k] → lax.all_to_all splitting dim 0 and concatenating dim 0.
    """
    g = _resolve_group(group)
    if in_tensor_list is not None and isinstance(in_tensor_list, (list, tuple)):
        v = jnp.concatenate([raw(t)[None] for t in in_tensor_list], axis=0)
        vflat = v.reshape((-1,) + v.shape[2:])
    else:
        vflat = raw(out_tensor_list if in_tensor_list is None else in_tensor_list)
    if _in_trace(vflat):
        out = lax.all_to_all(
            vflat.reshape((g.nranks, -1) + vflat.shape[1:]),
            _axes(g),
            split_axis=0,
            concat_axis=0,
            tiled=False,
        )
        out = out.reshape((-1,) + vflat.shape[1:])
    else:
        out = jnp.asarray(vflat)
    if in_tensor_list is not None and isinstance(out_tensor_list, list):
        chunks = out.reshape((g.nranks, -1) + out.shape[1:])
        for i in range(g.nranks):
            out_tensor_list.append(Tensor(chunks[i, 0]))
        return out_tensor_list
    return _wrap_like(out_tensor_list, out)


def alltoall_single(
    out_tensor, in_tensor=None, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True
):
    g = _resolve_group(group)
    v = raw(in_tensor if in_tensor is not None else out_tensor)
    if _in_trace(v):
        out = lax.all_to_all(
            v.reshape((g.nranks, -1) + v.shape[1:]),
            _axes(g),
            split_axis=0,
            concat_axis=0,
        ).reshape(v.shape)
    else:
        out = jnp.asarray(v)
    if in_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._rebind(out)
        return out_tensor
    return _wrap_like(out_tensor, out)


# ------------------------------------------------------------------ p2p & misc
def ppermute(tensor, perm: Sequence, group=None):
    """Collective-permute (the TPU replacement for NCCL send/recv pairs —
    SURVEY.md §2.3 PP row: `send_v2/recv_v2` → `lax.ppermute` over ICI)."""
    g = _resolve_group(group)
    v = raw(tensor)
    if not _in_trace(v):
        raise RuntimeError(
            "ppermute/send/recv are compiled collectives on TPU: call inside "
            "a shard_map-traced region (see paddle_tpu.distributed.shard_map)"
        )
    return _wrap_like(tensor, lax.ppermute(v, _axes(g), list(perm)))


def send(tensor, dst=0, group=None, sync_op=True):
    g = _resolve_group(group)
    n = g.nranks
    # A lone send in SPMD is expressed as the shifted permutation ring.
    return ppermute(tensor, [(i, dst) for i in range(n)], group=g)


def recv(tensor, src=0, group=None, sync_op=True):
    g = _resolve_group(group)
    n = g.nranks
    return ppermute(tensor, [(src, i) for i in range(n)], group=g)


isend = send
irecv = recv


def barrier(group=None):
    g = _resolve_group(group)
    # Eager barrier: synchronize all outstanding device work.
    try:
        jax.block_until_ready(jax.device_put(jnp.zeros((), jnp.float32)))
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    v = raw(tensor)
    if not _in_trace(v):
        jax.block_until_ready(v)
    return tensor


# stream.* namespace parity (paddle.distributed.stream)
class stream:
    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    alltoall_single = staticmethod(alltoall_single)
    broadcast = staticmethod(broadcast)
    scatter = staticmethod(scatter)
    reduce = staticmethod(reduce)
    send = staticmethod(send)
    recv = staticmethod(recv)


class P2POp:
    """paddle.distributed.P2POp parity: a deferred point-to-point op for
    batch_isend_irecv (reference: communication/batch_isend_irecv.py)."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (send, recv, isend, irecv):
            raise ValueError("P2POp op must be paddle.distributed.send/recv")
        if not isinstance(tensor, Tensor):
            raise TypeError(
                "P2POp tensor must be a paddle Tensor (recv rebinds it "
                "in place; a raw array cannot receive)"
            )
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Execute a batch of P2POps as fused collective permutes.

    Single-controller SPMD semantics: the program is traced ONCE for all
    ranks, so per-rank divergent P2P declarations cannot exist. Each
    declared op is therefore interpreted as a UNIFORM RELATIVE SHIFT —
    `P2POp(send, t, peer)` means "every rank r sends t to
    (r + (peer - my_rank)) % n" — which is exactly the symmetric
    ring/neighbor pattern the reference's pipeline codes use
    batch_isend_irecv for. Each send becomes one `lax.ppermute`; each recv
    must match a send with the complementary shift and has its tensor
    rebound to that permute's output. Recv-only batches (no payload
    visible to the trace) and unmatched recvs raise. Must run inside a
    shard_map-traced region, like send/recv. Returns [] (synchronous).
    """
    sends = [p for p in p2p_op_list if p.op in (send, isend)]
    recvs = [p for p in p2p_op_list if p.op in (recv, irecv)]
    if not sends and not recvs:
        return []
    if not sends:
        raise ValueError(
            "batch_isend_irecv under SPMD needs at least one send in the "
            "batch: a traced program has no rank-divergent branches, so a "
            "recv-only batch has no payload to transmit"
        )
    g = _resolve_group(sends[0].group)
    n = g.nranks
    me = max(g.rank, 0)
    out_by_shift = {}
    for p in sends:
        shift = (g.get_group_rank(p.peer) if p.peer in g.ranks else p.peer)
        shift = (shift - me) % n
        if shift in out_by_shift:
            raise ValueError(
                f"two sends with the same relative shift {shift}; their "
                "payloads would collide in one permutation"
            )
        perm = [(i, (i + shift) % n) for i in range(n)]
        out_by_shift[shift] = ppermute(p.tensor, perm, group=g)
    for p in recvs:
        src = (g.get_group_rank(p.peer) if p.peer in g.ranks else p.peer)
        shift = (me - src) % n
        if shift not in out_by_shift:
            raise ValueError(
                f"recv from relative offset {shift} has no matching send "
                f"in the batch (sends cover shifts {sorted(out_by_shift)})"
            )
        p.tensor._rebind(raw(out_by_shift[shift]))
    return []


def broadcast_object_list(object_list, src=0, group=None):
    """SPMD semantics: every process already holds the replicated objects
    (same as all_gather_object's honest model) — the list is returned
    unchanged; rank-mismatch is impossible in single-controller SPMD."""
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0, group=None):
    """Pick this rank's object from src's list (host-side SPMD: the full
    list is already replicated)."""
    g = _resolve_group(group)
    if in_object_list is None:
        raise ValueError("scatter_object_list needs in_object_list on src")
    idx = max(g.rank, 0)
    out_object_list.append(in_object_list[idx])
    return out_object_list


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather tensors to dst (paddle.distributed.gather). Traced form:
    all_gather then use the result on dst (XLA has no single-destination
    gather over ICI; the all-gather is what the hardware would run)."""
    g = _resolve_group(group)
    v = raw(tensor)
    if _in_trace(v):
        outs = lax.all_gather(v, _axes(g), axis=0, tiled=False)
        parts = [outs[i] for i in range(g.nranks)]
    else:
        parts = [jnp.asarray(v) for _ in range(g.nranks)]
    wrapped = [_wrap_like(tensor, p) for p in parts]
    if gather_list is not None and isinstance(gather_list, list):
        gather_list.extend(wrapped)
        return gather_list
    return wrapped


def get_backend(group=None):
    """Communication backend name: XLA collectives over ICI/DCN (the
    TPU-native answer to 'nccl'/'gloo')."""
    return "xla"
