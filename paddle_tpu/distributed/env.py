"""Parallel environment + process groups.

Reference capabilities (SURVEY.md §2.3 "Process bootstrap & launch",
"Rendezvous / store", "ProcessGroup / comm backend"):
  * `paddle.distributed.init_parallel_env` — TCPStore rendezvous + default
    ProcessGroupNCCL creation (`python/paddle/distributed/parallel.py`).
  * `paddle.distributed.new_group(ranks)` — per-subgroup NCCL communicator.
  * `ParallelEnv` — rank/world_size/device id from launcher env vars.

TPU-native design: rendezvous is JAX's built-in coordination service
(`jax.distributed.initialize` — one process per *host*, devices discovered
via PJRT). A "Group" is not a communicator but a named slice of the device
mesh; collectives on a group compile to XLA collectives with the group's
`axis_name` (see collective.py). In the single-controller SPMD world every
device is addressable from this process, so "rank" has two readings:
`process_index` (host rank — what multi-host launch sees) and device index
(the reference's per-GPU rank). We expose the device reading for API parity,
since the reference maps one rank per accelerator.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .. import observability as _obs
from . import mesh as _mesh


class ParallelEnv:
    """paddle.distributed.ParallelEnv parity."""

    def __init__(self):
        self._env_rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def local_rank(self) -> int:
        return jax.process_index()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def nranks(self) -> int:
        return get_world_size()

    @property
    def device_id(self) -> int:
        return 0

    @property
    def dev_id(self) -> int:
        return 0


class Group:
    """A collective group = an ordered set of devices + a mesh axis name.

    `axis_names` names the mesh axes this group spans; inside a traced/
    shard_map region collectives on the group reduce over those axes. The
    group also carries a private 1-D mesh over its devices for eager
    resharding-style collectives.
    """

    _next_gid = 0

    def __init__(
        self,
        ranks: Sequence[int],
        axis_names: Optional[Sequence[str]] = None,
        mesh: Optional[Mesh] = None,
        name: Optional[str] = None,
    ):
        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.id = Group._next_gid
        Group._next_gid += 1
        self.name = name or f"group_{self.id}"
        self.axis_names = tuple(axis_names) if axis_names else (f"_g{self.id}",)
        if mesh is None:
            devs = [jax.devices()[r] for r in self.ranks]
            mesh = Mesh(np.array(devs), (self.axis_names[0],))
        self.mesh = mesh

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        # Single-controller: this process owns all devices; report the first
        # local one for parity with scripts that branch on group rank.
        local = {d.id for d in jax.local_devices()}
        for i, r in enumerate(self.ranks):
            if r in local:
                return i
        return -1

    def get_group_rank(self, rank: int) -> int:
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks}, axes={self.axis_names})"


_default_group: Optional[Group] = None
_groups: List[Group] = []


def is_initialized() -> bool:
    return _default_group is not None


def init_parallel_env() -> Group:
    """Initialize the default (world) group over all devices.

    Multi-host: if launch-set coordination env vars are present
    (PADDLE_MASTER / JAX_COORDINATOR_ADDRESS + world size), bootstrap the
    JAX distributed runtime first so jax.devices() spans all hosts —
    replacing the reference's TCPStore + NCCL-unique-id exchange.
    """
    global _default_group
    if _default_group is not None:
        return _default_group
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get("PADDLE_MASTER")
    nproc = os.environ.get("PADDLE_TRAINERS_NUM") or os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("PADDLE_TRAINER_ID") or os.environ.get("JAX_PROCESS_ID")
    if coord and nproc and int(nproc) > 1 and not jax._src.distributed.global_state.client:
        # CPU backend: cross-process collectives need an explicit transport
        # (gloo); without it every multi-process program fails at compile
        # with "Multiprocess computations aren't implemented on the CPU
        # backend". Must be set before backend init. TPU needs nothing —
        # collectives ride ICI/DCN natively.
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except Exception:
                pass  # knob absent on this jax: keep prior behavior
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(pid or 0),
        )
        # hung-rank detection: when launch exported a heartbeat interval,
        # join the store-backed watchdog so a wedged peer fails the job
        # with a diagnosis instead of stalling every collective forever
        from ..runtime.watchdog import maybe_start_from_env

        maybe_start_from_env()
        if _obs.enabled():
            _obs.event("init_parallel_env", coordinator=coord,
                       world_size=int(nproc), process_id=int(pid or 0),
                       local_devices=jax.local_device_count())
            # ranks that never call fleet_sync themselves still contribute
            # to fleet_metrics.json on a clean exit
            import atexit

            from ..observability.fleet import fleet_sync_atexit
            atexit.register(fleet_sync_atexit)
    world = list(range(len(jax.devices())))
    _default_group = Group(world, axis_names=None, name="world")
    _groups.append(_default_group)
    if _mesh.get_global_mesh() is None:
        _mesh.set_global_mesh(_default_group.mesh)
    return _default_group


def get_default_group() -> Group:
    if _default_group is None:
        init_parallel_env()
    return _default_group


def _resolve_group(group: Optional[Group]) -> Group:
    return group if group is not None else get_default_group()


def new_group(ranks: Optional[Sequence[int]] = None, backend=None, timeout=None) -> Group:
    """paddle.distributed.new_group parity — a subgroup over device ids."""
    if ranks is None:
        ranks = list(range(len(jax.devices())))
    g = Group(list(ranks))
    _groups.append(g)
    return g


def get_rank(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.rank
    return jax.process_index() if not is_initialized() else _default_group.rank


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    return len(jax.devices())


def destroy_process_group(group: Optional[Group] = None):
    global _default_group
    if group is None or group is _default_group:
        _default_group = None
        _groups.clear()
    elif group in _groups:
        _groups.remove(group)
