"""GroupSharded (ZeRO) stages as placement policies.

Reference capability (SURVEY.md §2.3 "Sharding (ZeRO-1/2/3)"):
`GroupShardedStage1/2/3` in
`python/paddle/distributed/fleet/meta_parallel/sharding/` implement hand-
rolled optimizer-state/grad/param sharding with explicit broadcast /
reduce-scatter / gather hooks over the sharding NCCL group.

TPU-native design: each stage is a *data placement*, not an engine —
  stage 1: optimizer states sharded over the `sharding` mesh axis
           (HybridParallelOptimizer does this placement);
  stage 2: + gradients — under a compiled step, grads are transient values
           inside one XLA program; GSPMD already materializes them sharded
           where the consumer (the sharded optimizer update) wants them, so
           stage 2 collapses into stage 1 at the API level;
  stage 3: + parameters sharded (`shard_model_parameters(fsdp=True)`); XLA's
           latency-hiding scheduler overlaps the param all-gathers with
           compute — the hand-written gather/release hooks of the reference
           become compiler work.
"""
from __future__ import annotations

from ....nn.layer import Layer
from .. import HybridParallelOptimizer, shard_model_parameters


class _GroupShardedBase(Layer):
    stage = 1

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False, **kwargs):
        super().__init__()
        self._layers = layer
        shard_model_parameters(layer, fsdp=(self.stage == 3))
        self._optim = (
            optimizer
            if optimizer is None or isinstance(optimizer, HybridParallelOptimizer)
            else HybridParallelOptimizer(optimizer)
        )

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)


class GroupShardedStage1(_GroupShardedBase):
    stage = 1


class GroupShardedStage2(_GroupShardedBase):
    stage = 2


class GroupShardedStage3(_GroupShardedBase):
    stage = 3
