"""TensorParallel model wrapper (fleet.meta_parallel.TensorParallel parity).

Reference: wraps the model to broadcast non-distributed params across the mp
group at init and sync grads. TPU-native: parameter placement (device_put
with each param's dist_spec) makes every rank's view consistent by
construction — the wrapper only performs placement, then defers to the model.
"""
from __future__ import annotations

from ....nn.layer import Layer


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        from .. import shard_model_parameters

        self._layers = layers
        shard_model_parameters(layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
