from ..layers.mpu import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
from .pipeline_parallel import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    PipelineParallel,
    SharedLayerDesc,
    SpmdPipeline,
)
from .sharding import GroupShardedStage1, GroupShardedStage2, GroupShardedStage3  # noqa: F401
from .tensor_parallel import TensorParallel  # noqa: F401
