"""Pipeline parallelism (fleet.meta_parallel pipeline parity), TPU-native.

Reference capability (SURVEY.md §2.3 "Pipeline parallel"):
`PipelineLayer` segments a LayerDesc list into stages
(`parallel_layers/pp_layers.py`); `PipelineParallel.train_batch` runs 1F1B
over micro-batches with NCCL P2P between stage ranks
(`pipeline_parallel.py`, `pp_utils/p2p_communication.py`).

TPU-native design (SURVEY.md §7 step 7 and "Hard parts"): no NCCL P2P exists;
the schedule lives *inside one compiled program*:

* `SpmdPipeline` — the workhorse. N structurally-identical blocks' parameters
  are stacked along a leading stage/layer dim sharded over the `pp` mesh
  axis. Forward is either a `lax.scan` over layers (pp=1: plain layer
  stacking) or a **circular micro-batch schedule inside `shard_map`**: each
  pp rank applies its resident layers and hands activations to the next
  stage with `lax.ppermute` (collective-permute over ICI — the send_v2/
  recv_v2 replacement). `jax.grad` differentiates straight through the
  schedule, so fwd+bwd+update still compile as ONE XLA program; remat on
  blocks bounds activation memory (the role 1F1B plays in the reference).

* `PipelineLayer` keeps the LayerDesc/seg_method API: it instantiates the
  descs, finds the longest homogeneous run (the transformer body), and folds
  it into a `SpmdPipeline`; pre/suffix layers (embedding, head) run on all
  stages (replicated or TP-sharded), which is cheap under SPMD.
"""
from __future__ import annotations

import functools
import os
import time
import warnings
import re
from dataclasses import dataclass, replace
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .... import observability as _obs
from ....framework.core import Tensor
from ....framework.op import defop, raw
from ....nn.layer import Layer, Parameter
from ... import mesh as _mesh
from ...collective import psum_f32safe as _psum_f32safe


# ------------------------------------------------- schedule configuration --
PP_SCHEDULES = ("gpipe", "1f1b", "zero_bubble")

_PP_TRUE = {"1", "on", "true", "yes"}


@dataclass(frozen=True)
class PpScheduleConfig:
    """Resolved pipeline-schedule knobs (docs/PIPELINE.md).

    ``schedule`` picks how the compiled program orders micro-batch work:
    ``gpipe`` (all-forward-then-derived-backward, the historical default),
    ``1f1b`` (explicitly scheduled backward ring, reverse tick order), or
    ``zero_bubble`` (1f1b with backward split into input-grad ring ticks +
    deferred bulk weight-grad). ``virtual_pp_degree`` is the interleaving
    factor V: chunk c of the layer stack lives on physical stage c % S and
    the flush bubble shrinks by V.
    """

    schedule: str = "gpipe"
    virtual_pp_degree: int = 1


def _strategy_pp_config(strategy) -> PpScheduleConfig:
    cfg = PpScheduleConfig()
    if strategy is None:
        return cfg
    sub = dict(getattr(strategy, "pipeline_configs", {}) or {})
    sched = str(sub.get("schedule", cfg.schedule)).strip().lower()
    if sched not in PP_SCHEDULES:
        raise ValueError(
            f"pipeline_configs.schedule={sched!r} not in {PP_SCHEDULES}")
    v = max(int(sub.get("virtual_pp_degree", cfg.virtual_pp_degree)), 1)
    return PpScheduleConfig(schedule=sched, virtual_pp_degree=v)


def resolve_pp_schedule(strategy=None) -> PpScheduleConfig:
    """Strategy knobs overridden by ``PADDLE_TPU_PP_SCHEDULE``.

    Env grammar (case-insensitive), mirroring PADDLE_TPU_GRAD_COMM:
      ``gpipe`` / ``1f1b`` / ``zero_bubble``   bare schedule tokens
      comma list of ``k=v``                    ``schedule=1f1b,virtual=2``
                                               (``vpp`` / ``virtual_pp_degree``
                                               are aliases of ``virtual``)
      bare tokens compose with k=v ones:       ``zero_bubble,virtual=2``
    """
    if strategy is None:
        from ... import fleet as _fleet

        strategy = _fleet.fleet_strategy()
    cfg = _strategy_pp_config(strategy)
    raw_env = os.environ.get("PADDLE_TPU_PP_SCHEDULE", "").strip().lower()
    if not raw_env:
        return cfg
    for part in raw_env.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            if part in PP_SCHEDULES:
                cfg = replace(cfg, schedule=part)
            else:
                raise ValueError(
                    f"PADDLE_TPU_PP_SCHEDULE: bad token {part!r} "
                    f"(want k=v or a schedule from {PP_SCHEDULES})")
            continue
        k, v = (s.strip() for s in part.split("=", 1))
        if k == "schedule":
            if v not in PP_SCHEDULES:
                raise ValueError(
                    f"PADDLE_TPU_PP_SCHEDULE schedule={v!r} not in "
                    f"{PP_SCHEDULES}")
            cfg = replace(cfg, schedule=v)
        elif k in ("virtual", "vpp", "virtual_pp_degree"):
            cfg = replace(cfg, virtual_pp_degree=max(int(v), 1))
        else:
            raise ValueError(f"PADDLE_TPU_PP_SCHEDULE: unknown key {k!r}")
    return cfg


class LayerDesc:
    """Deferred layer construction (reference: pp_layers.LayerDesc)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-shared layer (e.g. tied embedding/head). Single-controller SPMD
    holds one copy, so 'sharing across stages' is simple object sharing."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


def _cfg_sig(layer: Layer):
    """Primitive/callable config fingerprint: Dropout(p=0.1) vs Dropout(
    p=0.5), or wrappers holding different forward functions, must not fold
    together (conservative: differing benign attrs merely prevent folding,
    which is always safe)."""
    out = []
    for k, v in sorted(vars(layer).items()):
        if k == "training":
            continue  # runtime mode flag, not identity
        if isinstance(v, (bool, int, float, str, type(None))):
            out.append((k, v))
        elif isinstance(v, (tuple, list)) and all(
            isinstance(i, (bool, int, float, str, type(None))) for i in v
        ):
            out.append((k, tuple(v)))
        elif isinstance(v, dict) and all(
            isinstance(i, (bool, int, float, str, type(None)))
            for i in v.values()
        ):
            out.append((k, tuple(sorted(v.items()))))
        elif callable(v) and not isinstance(v, (Layer, Tensor)):
            out.append((k, getattr(v, "__qualname__", type(v).__name__)))
    return tuple(out)


def _type_sig(layer: Layer):
    """Recursive structural identity: type chain + per-layer config
    fingerprint. Sequential(Linear, ReLU) must NOT match
    Sequential(Linear, Tanh), and same-typed blocks with different config
    (dropout rate, wrapped forward fn) must not fold either — folding runs
    every block through the template's forward."""
    return (
        type(layer).__name__,
        _cfg_sig(layer),
        tuple(_type_sig(l) for l in layer._sub_layers.values() if l is not None),
    )


def _param_sig(layer: Layer):
    return (_type_sig(layer),) + tuple(
        (n, tuple(raw(p).shape), str(raw(p).dtype)) for n, p in layer.named_parameters()
    ) + tuple(
        (n, tuple(raw(b).shape), str(raw(b).dtype)) for n, b in layer.named_buffers()
    )


class SpmdPipeline(Layer):
    """Stack of identical blocks, layer dim sharded over `pp`."""

    def __init__(
        self,
        blocks: Sequence[Layer],
        num_stages: Optional[int] = None,
        num_microbatches: Optional[int] = None,
        recompute_block: bool = False,
        num_virtual_stages: Optional[int] = None,
        recompute_granularity: str = "full",
        schedule: Optional[str] = None,
    ):
        super().__init__()
        blocks = list(blocks)
        if not blocks:
            raise ValueError("SpmdPipeline needs at least one block")
        sig = _param_sig(blocks[0])
        for b in blocks[1:]:
            if _param_sig(b) != sig:
                raise ValueError("SpmdPipeline blocks must be structurally identical")
        self.num_layers = len(blocks)
        m = _mesh.get_global_mesh()
        self.num_stages = num_stages or _mesh.mesh_axis_size("pp")
        if schedule is not None and schedule not in PP_SCHEDULES:
            raise ValueError(f"schedule={schedule!r} not in {PP_SCHEDULES}")
        # None = resolve at forward time (strategy/env may change per run)
        self._schedule = schedule
        if num_virtual_stages is None:
            # unset: adopt the strategy/env virtual degree when it divides
            # the stack, else degrade to non-interleaved (model-zoo call
            # sites pass nothing; an explicit argument keeps the hard error)
            s_eff = max(self.num_stages, 1)
            v = resolve_pp_schedule().virtual_pp_degree if s_eff > 1 else 1
            if v > 1 and self.num_layers % (s_eff * v) != 0:
                warnings.warn(
                    f"virtual_pp_degree={v} does not divide "
                    f"{self.num_layers} layers over {s_eff} stages; "
                    "falling back to non-interleaved pipeline",
                    stacklevel=2)
                v = 1
            num_virtual_stages = v
        self.num_virtual_stages = max(int(num_virtual_stages), 1)
        n_chunks = max(self.num_stages, 1) * self.num_virtual_stages
        if self.num_layers % n_chunks != 0:
            raise ValueError(
                f"{self.num_layers} layers not divisible by {self.num_stages} "
                f"stages x {self.num_virtual_stages} virtual stages"
            )
        self.num_microbatches = num_microbatches
        self.recompute_block = recompute_block
        from ..utils.recompute_helper import policy_for_granularity

        policy_for_granularity(recompute_granularity)  # fail fast on typos
        self.recompute_granularity = recompute_granularity
        # Interleaved (virtual-pp) layout: chunk c of layer range lives on
        # physical stage c % S (reference: interleaved 1F1B — SURVEY.md §2.3
        # "Pipeline parallel" / virtual-pp). Stacking order is s-major so a
        # P("pp") shard of the leading dim hands stage s its V chunks
        # contiguously; _layer_order maps stacked position -> original layer.
        S, V = max(self.num_stages, 1), self.num_virtual_stages
        chunk_len = self.num_layers // n_chunks
        order = sorted(
            range(self.num_layers),
            key=lambda l: ((l // chunk_len) % S, (l // chunk_len) // S, l),
        )
        self._layer_order = order
        self._inv_order = np.argsort(order)
        # template block is NOT a registered sublayer (its params are absorbed
        # into the stacked ones); hide it from Layer.__setattr__.
        self._template_holder = [blocks[0]]

        def stack_leaves(list_fn):
            """Stack each (name, leaf) of the template across all blocks in
            interleaved `order` along a new leading layer dim."""
            per_block = [[raw(v) for _, v in list_fn(b)] for b in blocks]
            out = []
            for i, (n, tmpl_leaf) in enumerate(list_fn(blocks[0])):
                stacked = jnp.stack(
                    [per_block[l][i] for l in order], axis=0
                )
                out.append((n, tmpl_leaf, stacked))
            return out

        self._tparams = [p for _, p in blocks[0].named_parameters()]
        self._stacked: List[Parameter] = []
        for n, tp, stacked in stack_leaves(lambda b: list(b.named_parameters())):
            sp = Parameter(stacked, trainable=tp.trainable, name=f"stacked_{n}")
            base_spec = list(getattr(tp, "dist_spec", None) or P())
            base_spec += [None] * (stacked.ndim - 1 - len(base_spec))
            sp.dist_spec = P("pp", *base_spec)
            self.add_parameter(n.replace(".", "__"), sp)
            self._stacked.append(sp)
        # read-only buffers (rotary caches, masks, ...) stack like params;
        # buffer MUTATION inside pipelined blocks (train-mode batchnorm) is
        # not supported — the schedule compiles the blocks functionally
        self._tbuffers = [b for _, b in blocks[0].named_buffers()]
        self._stacked_bufs: List[Tensor] = []
        for n, _, stacked in stack_leaves(lambda b: list(b.named_buffers())):
            sb = Tensor(stacked)
            sb.dist_spec = P("pp", *([None] * (stacked.ndim - 1)))
            self.register_buffer(n.replace(".", "__") + "_stacked", sb)
            self._stacked_bufs.append(sb)

    # -- modes: the template is NOT a registered sublayer (its params are
    #    absorbed into the stacked ones), so train()/eval() must be
    #    forwarded explicitly or its dropout/batchnorm flags go stale ----
    def train(self):
        super().train()
        self._template_holder[0].train()
        return self

    def eval(self):
        super().eval()
        self._template_holder[0].eval()
        return self

    # -- functional application of the template with given leaf values -------
    def _apply_block(self, leaf_vals, x, *extra):
        tmpl = self._template_holder[0]
        nb = len(self._tbuffers)
        p_vals = leaf_vals[: len(leaf_vals) - nb] if nb else leaf_vals
        b_vals = leaf_vals[len(leaf_vals) - nb:] if nb else ()
        originals = [p._value for p in self._tparams]
        orig_bufs = [b._value for b in self._tbuffers]
        # the stack wraps this whole apply in jax.checkpoint; a block whose
        # own forward also calls recompute() would nest and recompute the
        # forward twice in backward — flip its flag only for this apply
        # (never mutate the caller-owned block permanently)
        orig_rc = getattr(tmpl, "_use_recompute", False)
        try:
            for p, v in zip(self._tparams, p_vals):
                p._value = v
            for b, v in zip(self._tbuffers, b_vals):
                b._value = v
            if self.recompute_block and orig_rc:
                tmpl._use_recompute = False
            out = tmpl(Tensor(x), *extra)
            return raw(out)
        finally:
            if self.recompute_block and orig_rc:
                tmpl._use_recompute = orig_rc
            for p, v in zip(self._tparams, originals):
                p._value = v
            for b, v in zip(self._tbuffers, orig_bufs):
                b._value = v

    def forward(self, x, *extra):
        """``extra`` — per-call tensors every block receives unchanged (an
        encoder's attention mask). Supported on the layer-fold (scan) path
        only; the micro-batch pipeline schedules take a single tensor.

        ``x`` and ``extra`` pass into the defop UN-unwrapped: the defop
        records Tensor leaves as differentiable tape inputs, so the eager
        tape edge back to the embeddings (or a differentiable mask) stays
        intact — a pre-emptive ``raw()`` here silently severed it."""
        return _pipeline_forward(
            x,
            *[p for p in self._stacked],
            *[b for b in self._stacked_bufs],
            *extra,
            n_extra=len(extra),
            pipe=self,
        )

    def schedule_info(self, batch_size: int,
                      schedule: Optional[str] = None) -> dict:
        """Step/bubble accounting for the compiled schedule.

        Per-step cost is expressed in full-stage layer passes (L/S layers):
        the V=1 circular schedule does 1.0 per step; the phased interleaved
        schedule does one chunk (= 1/V) per step. `bubble_fraction` is the
        forward idle-time share per pipeline flush — the quantity
        interleaved 1F1B exists to shrink (reference: fleet interleaved
        1F1B).

        Analytic fwd+bwd model (docs/PIPELINE.md §3), unit costs per
        micro-batch per full stage: F=1, full B=2, input-grad B=1,
        weight-grad W=1 (per-chunk costs divide by V):
        `fwd_bwd_total_cost` / `analytic_bubble_fraction` — the schedule's
        planned flush time and idle share (gpipe and synchronous 1f1b tie;
        zero_bubble fills the drain with deferred weight-grad, reaching 0
        when M >= 2(S-1)/V). `measured_bubble_fraction` is the idle-cell
        fraction of the compiled (stage, tick) schedule table (fwd + bwd
        grids; zero_bubble's deferred weight-grad scan counts as dense
        ticks), i.e. what the compiled program actually schedules, and is
        what `pp_bubble_fraction` reports via telemetry.
        """
        S, V = self.num_stages, self.num_virtual_stages
        M = _choose_microbatches(batch_size, self.num_microbatches or S, warn=False)
        sched = (schedule or self._schedule
                 or resolve_pp_schedule().schedule)
        if _uses_scan_fallback(S):
            S = 1
        if S <= 1:
            return {"steps": 1, "step_cost": float(M), "total_cost": float(M),
                    "ideal_cost": float(M), "bubble_fraction": 0.0, "M": M,
                    "schedule": "fold", "fwd_bwd_total_cost": 3.0 * M,
                    "analytic_bubble_fraction": 0.0,
                    "measured_bubble_fraction": 0.0,
                    "schedule_ticks": M, "act_microbatches": M}
        if sched == "gpipe" and V == 1:
            steps, cost = M + S - 1, 1.0
        else:
            groups = -(-M // S)
            steps, cost = groups * S * V + S - 1, 1.0 / V
        total = steps * cost
        busy = V * M                   # scheduled cells per stage per grid
        ticks = 2 * steps + (busy if sched == "zero_bubble" else 0)
        idle = 2 * (steps - busy)
        fill = (S - 1) / V
        if sched == "zero_bubble":
            fb_total = 3.0 * M + max(0.0, 2.0 * fill - M)
        else:
            fb_total = 3.0 * M + 3.0 * fill
        return {"steps": steps, "step_cost": cost, "total_cost": total,
                "ideal_cost": float(M), "bubble_fraction": 1.0 - M / total,
                "M": M, "schedule": sched,
                "fwd_bwd_total_cost": fb_total,
                "analytic_bubble_fraction": 1.0 - 3.0 * M / fb_total,
                "measured_bubble_fraction": idle / ticks,
                "schedule_ticks": ticks,
                "act_microbatches": busy}


def fold_or_list(blocks, fold: bool, recompute: bool = False,
                 recompute_granularity: str = "full"):
    """Model-zoo construction helper: the layer-fold stack (ONE lax.scan
    over layer-stacked params — compile O(1) in depth) when ``fold``, else
    a plain LayerList. One definition for GPT/Llama/BERT/ERNIE."""
    if fold and len(blocks) > 1:
        return SpmdPipeline(blocks, num_stages=1, recompute_block=recompute,
                            recompute_granularity=recompute_granularity)
    from ....nn.layer import LayerList

    return LayerList(blocks)


def run_stack(stack, x, *extra):
    """Apply a fold_or_list stack: scans the folded form, loops the list.
    ``extra`` (e.g. an encoder's attention mask) goes to every block."""
    if isinstance(stack, SpmdPipeline):
        return stack(x, *extra)
    for blk in stack:
        x = blk(x, *extra) if extra else blk(x)
    return x


def _uses_scan_fallback(num_stages: int) -> bool:
    """True when the pipeline runs the layer-stacked scan (no micro-batch
    schedule): no mesh, no `pp` axis, or a pp axis narrower than the stage
    count. Single source of truth for forward AND schedule_info."""
    m = _mesh.get_global_mesh()
    return (
        num_stages <= 1
        or m is None
        or "pp" not in m.shape
        or m.shape["pp"] < num_stages
    )


def _choose_microbatches(batch: int, requested: int, warn: bool = True) -> int:
    """Largest micro-batch count <= requested that divides the batch.

    Round-1 behavior silently fell back to M=1 (maximum bubble) whenever
    batch % requested != 0 — a perf cliff. Now we degrade minimally and
    loudly (VERDICT round 1, weak #2).
    """
    m = max(1, min(int(requested), int(batch)))
    while batch % m != 0:
        m -= 1
    if warn and m != requested:
        warnings.warn(
            f"num_microbatches={requested} does not divide batch={batch}; "
            f"using {m} micro-batches instead (pipeline bubble grows — pad "
            "the batch or pick a divisor)",
            stacklevel=3,
        )
    return m


def phased_stage_table(S: int, V: int, M: int, schedule: str = "1f1b"):
    """Host-side mirror of the phased schedule decode (the arithmetic in
    ``spmd_fn_scheduled.decode``): per stage, the ordered list of
    ``(tick, kind, mb_idx, chunk)`` ops, where kind is ``"F"`` or ``"B"``.

    This is the MPMD per-stage tick driver (``distributed/mpmd.py``): a
    stage runner replays exactly this table against its queues, so 1F1B
    ordering and micro-batch accounting carry over from the SPMD compiled
    schedules unchanged. Forward ops come out in tick order; backward ops
    in the order the SPMD custom-vjp executes them:

    * ``gpipe`` / default phased order — reverse tick order (the compiled
      backward replays the ring backwards, so gradient accumulation per
      stage runs micro-batches last-to-first);
    * ``1f1b`` streaming order — after a warmup of ``min(M, S - s)``
      forwards, stage s alternates one backward (ascending mb) with one
      forward, capping in-flight stashes at ``S - s`` instead of M.

    Both orders accumulate the same gradient sum (reassociation only;
    the MPMD-vs-SPMD trajectory gate pins the numerics <=1e-5).
    """
    if schedule not in PP_SCHEDULES:
        raise ValueError(f"schedule={schedule!r} not in {PP_SCHEDULES}")
    groups = -(-M // S)
    n_steps = groups * S * V + S - 1
    fwd = {s: [] for s in range(S)}
    for t in range(n_steps):
        for s in range(S):
            rel_total = t - s
            if rel_total < 0:
                continue
            g = rel_total // (S * V)
            rel = rel_total - g * S * V
            k_raw = rel // S
            m_local = rel % S
            if g >= groups or g * S + m_local >= M or k_raw >= V:
                continue
            fwd[s].append((t, "F", g * S + m_local, k_raw))
    table = {}
    for s in range(S):
        f_ops = fwd[s]
        b_ops = [(2 * n_steps - 1 - t, "B", mb, k)
                 for (t, _, mb, k) in reversed(f_ops)]
        if schedule == "1f1b" and V == 1:
            # warmup then strict 1B1F, backward ascending by micro-batch
            w = min(M, S - s)
            b_asc = sorted(b_ops, key=lambda op: op[2])
            ops, fi, bi = list(f_ops[:w]), w, 0
            while bi < len(b_asc):
                ops.append(b_asc[bi])
                bi += 1
                if fi < len(f_ops):
                    ops.append(f_ops[fi])
                    fi += 1
            table[s] = ops
        else:
            table[s] = f_ops + b_ops
    return table


@defop(name="spmd_pipeline")
def _pipeline_forward(x, *stacked_vals, pipe: SpmdPipeline, n_extra: int = 0):
    m = _mesh.get_global_mesh()
    S = pipe.num_stages
    block = pipe._apply_block
    ckpt_policy = None
    if pipe.recompute_block:
        # "full" granularity (save block inputs only) is the only policy
        # that scales here: any saveable intermediate is stacked across the
        # whole layer dim by the scan below ([L, B, T, ffn] stashes OOM'd a
        # v5e at 16 layers under dots_saveable — measured round 5).
        from ..utils.recompute_helper import policy_for_granularity

        gran = getattr(pipe, "recompute_granularity", "full")
        # each stage's scan stacks only its own chunk of layers
        chunk = pipe.num_layers // (
            max(pipe.num_stages, 1) * pipe.num_virtual_stages)
        if gran != "full" and chunk >= 8 and not getattr(
                pipe, "_warned_gran_stack", False):
            object.__setattr__(pipe, "_warned_gran_stack", True)
            warnings.warn(
                f"recompute_granularity={gran!r} with {chunk} layers "
                "scanned per stage: saveable intermediates stack across "
                "the scanned layer dim and can exhaust device memory "
                "(a 16-layer GPT-760M at seq 1024 OOMs a 16 GiB chip); "
                "use 'full' unless the per-stage stack is shallow",
                stacklevel=3)
        ckpt_policy = policy_for_granularity(gran)
        block = jax.checkpoint(block, policy=ckpt_policy)

    if n_extra:
        stacked_vals, extra = stacked_vals[:-n_extra], stacked_vals[-n_extra:]
    else:
        extra = ()

    if _uses_scan_fallback(S):
        # layer-stacked scan (the idiomatic big-model pattern: one block
        # compiled once, scanned over the layer dim); un-permute the
        # interleaved stacking back to original layer order first
        if pipe.num_virtual_stages > 1:
            inv = jnp.asarray(pipe._inv_order)
            ordered = tuple(v[inv] for v in stacked_vals)
        else:
            ordered = tuple(stacked_vals)

        # per-layer RNG keys ride the scan: the body is traced ONCE, so a
        # plain next_key() inside the template would hand every layer the
        # SAME dropout mask. Each layer instead derives its random ops
        # from its own key (and remat replays them identically). Gated on
        # training: an eval forward must not consume global RNG state.
        if getattr(pipe._template_holder[0], "training", False):
            from ....framework import rng as _rng

            keys = jax.random.split(_rng.next_key(), pipe.num_layers)

            def body(h, xs):
                leaves, lk = xs[:-1], xs[-1]
                with _rng.trace_key_scope(lk):
                    return block(leaves, h, *extra), None

            h, _ = lax.scan(body, x, (*ordered, keys))
        else:
            def body(h, leaves):
                return block(leaves, h, *extra), None

            h, _ = lax.scan(body, x, ordered)
        return h

    if extra:
        raise NotImplementedError(
            "SpmdPipeline: extra per-call args (attention masks, ...) are "
            "supported on the layer-fold path (num_stages=1) only; the "
            "micro-batch pipeline schedules move a single tensor between "
            "stages — fold the mask into the block input or its buffers")

    tmpl = pipe._template_holder[0]
    if getattr(tmpl, "training", False) and not getattr(
            pipe, "_warned_sched_dropout", False):
        if any("dropout" in type(l).__name__.lower() and getattr(l, "p", 0)
               for l in tmpl.sublayers(include_self=True)):
            object.__setattr__(pipe, "_warned_sched_dropout", True)
            warnings.warn(
                "SpmdPipeline micro-batch schedule with active dropout: the "
                "schedule body is traced once, so dropout masks repeat "
                "across layers and micro-batches within a step (the "
                "layer-fold path decorrelates per layer; full per-"
                "(layer, micro-batch) decorrelation in the pipeline "
                "schedules is a known limit). Set dropout to 0 for exact "
                "reference-equivalent pipeline training.",
                stacklevel=3)

    # ---- circular micro-batch schedule over the pp axis --------------------
    V = pipe.num_virtual_stages
    B = x.shape[0]
    M = _choose_microbatches(B, pipe.num_microbatches or S)
    mb = B // M
    sched_name = pipe._schedule or resolve_pp_schedule().schedule

    from ... import grad_comm as _grad_comm

    cfg = _grad_comm.resolve_config()
    n_params = len(pipe._stacked)
    leaf_specs = [getattr(sp, "dist_spec", None) or P()
                  for sp in (*pipe._stacked, *pipe._stacked_bufs)]

    # Batch-shard the schedule over the data axes: micro-batch rows (dim 1
    # of [M, mb, ...]) split across dp/sharding so per-device FLOPs track
    # the per-device batch instead of the global batch (the region used to
    # enter replicated and every device recomputed the full batch). Rows
    # are laid out so device d's slice is exactly the dim-0 shard the batch
    # already has outside the region: x row j*M + t -> xm[t, j].
    bs_axes = ()
    if cfg.pipeline_batch_shard:
        cand = _grad_comm.data_axes(m)
        gd = int(np.prod([m.shape[a] for a in cand])) if cand else 1
        if cand and gd > 1 and mb % gd == 0:
            bs_axes = cand
    if bs_axes:
        xm = x.reshape((mb, M) + x.shape[1:]).swapaxes(0, 1)
        data_spec = P(None, bs_axes if len(bs_axes) > 1 else bs_axes[0])
    else:
        xm = x.reshape((M, mb) + x.shape[1:])
        data_spec = P()

    # ZeRO-3 leaves stay sharded INSIDE the region: in_spec keeps the
    # committed `sharding` dim, a per-layer tiled all_gather inside the
    # (re-materialised) block reassembles the full layer, and its autodiff
    # transpose is the psum_scatter that hands the update sharded
    # gradients. Only the current layer is ever full per device.
    S_sh = m.shape.get("sharding", 1)
    sharded_idx = []
    if cfg.zero_update and S_sh > 1:
        for i in range(n_params):
            k = _grad_comm.sharded_dim(leaf_specs[i], "sharding")
            if k is not None and k > 0:
                sharded_idx.append(i)
    z_set = frozenset(sharded_idx)
    z_layout = None
    if sharded_idx:
        z_layout = _grad_comm.make_shard_layout(
            sharded_idx,
            [tuple(stacked_vals[i].shape[1:]) for i in sharded_idx],
            [_grad_comm.sharded_dim(leaf_specs[i], "sharding") - 1
             for i in sharded_idx],
            S_sh)

    # Non-sharded PARAM leaves ride per-dtype fusion buckets: one flattened
    # (L, sum_i s_i) tensor per bucket enters at P("pp"), so the boundary
    # gradient all-reduce over the unmentioned data axes is ONE collective
    # per bucket instead of one per leaf (backward/comm overlap: earlier
    # buckets' reductions overlap later layers' backward compute).
    bucket_layouts = []
    if cfg.enable:
        by_dtype = {}
        for i in range(n_params):
            if i in z_set:
                continue
            by_dtype.setdefault(str(jnp.dtype(stacked_vals[i].dtype)),
                                []).append(i)
        for _, idxs in sorted(by_dtype.items()):
            shapes = [tuple(stacked_vals[i].shape) for i in idxs]
            its = [jnp.dtype(stacked_vals[i].dtype).itemsize for i in idxs]
            bucket_layouts.extend(_grad_comm.make_layouts(
                shapes, its, cfg.bucket_bytes, lead_dims=1, indices=idxs))
    bucketed = frozenset(i for lay in bucket_layouts for i in lay.indices)

    # region inputs: pass-through leaves first, then the packed buckets
    pass_idx = [i for i in range(len(stacked_vals)) if i not in bucketed]
    region_vals, region_specs = [], []
    for i in pass_idx:
        if i in z_set:
            ent = [None] * stacked_vals[i].ndim
            ent[0] = "pp"
            ent[_grad_comm.sharded_dim(leaf_specs[i], "sharding")] = "sharding"
            region_specs.append(P(*ent))
        else:
            region_specs.append(P("pp"))
        region_vals.append(stacked_vals[i])
    for lay in bucket_layouts:
        region_vals.append(
            _grad_comm.pack_bucket(stacked_vals, lay, lead_dims=1))
        region_specs.append(P("pp"))

    if bucket_layouts or z_layout is not None:
        L_layers = pipe.num_layers
        elems = L_layers * (sum(l.total for l in bucket_layouts)
                            + (z_layout.total if z_layout is not None else 0))
        wire_it = cfg.wire_itemsize if cfg.quantized else 4
        _grad_comm.record_build_stats(
            len(bucket_layouts) + (1 if z_layout is not None else 0),
            elems * 4, elems * wire_it)
        if bucket_layouts:
            _grad_comm.record_overlap_ratio(
                L_layers * bucket_layouts[0].total * 4, elems * 4)

    def _leaves_of(region):
        """Rebuild the per-leaf local list from pass-through + buckets; the
        wire_cast makes each bucket's boundary cotangent a quantized
        payload (f32-accumulated by the promoted psum)."""
        leaves = [None] * len(stacked_vals)
        for pos, i in enumerate(pass_idx):
            leaves[i] = region[pos]
        for b, lay in enumerate(bucket_layouts):
            bkt = region[len(pass_idx) + b]
            if cfg.quantized:
                bkt = _grad_comm.wire_cast(bkt, cfg.wire_dtype)
            for i, v in _grad_comm.unpack_bucket(bkt, lay, lead_dims=1):
                leaves[i] = v
        return tuple(leaves)

    # mp_comm activation wire: the per-layer ZeRO parameter gather is a
    # forward payload — ride the quantized all-gather (floored at bf16,
    # see MpCommConfig.param_gather_wire) when the wire is on
    from ... import mp_comm as _mp_comm
    _param_gather_wire = _mp_comm.resolve_config().param_gather_wire

    def _prep_layer(leaves):
        """Gather the ZeRO-sharded leaves of ONE layer (inside remat, so
        residuals stay sharded slices)."""
        if z_layout is None:
            return leaves
        out = list(leaves)
        for i, full in _grad_comm.gather_leaves(
                [leaves[i] for i in z_layout.indices], z_layout, "sharding",
                wire_dtype=cfg.wire_dtype if cfg.quantized else None,
                act_wire=_param_gather_wire):
            out[i] = full
        return tuple(out)

    if z_layout is None:
        sched_block = block
        sched_block_raw = pipe._apply_block
    else:
        def _gathered_block(leaves, h):
            return pipe._apply_block(_prep_layer(leaves), h)

        sched_block = (jax.checkpoint(_gathered_block, policy=ckpt_policy)
                       if pipe.recompute_block else _gathered_block)
        # the explicitly-scheduled backward recomputes each chunk from its
        # stashed input inside its own tick (inherent "full" remat), so it
        # uses the UNcheckpointed block — wrapping would recompute twice
        sched_block_raw = _gathered_block

    # the scheduled (1f1b / zero_bubble) backward re-traces the chunk body
    # per jax.vjp call; random ops must replay the FORWARD trace's bits
    # exactly or dropout masks diverge between the stashed forward and its
    # backward recompute (silently wrong gradients). One explicit
    # trace-scoped key pins every chunk application — fwd and bwd — to the
    # same deterministic stream (masks repeat across chunks/micro-batches,
    # the documented schedule-path limitation above).
    train_key = _rng_pp = None
    if sched_name != "gpipe" and getattr(tmpl, "training", False):
        from ....framework import rng as _rng_pp  # noqa: F811

        train_key = _rng_pp.next_key()

    def stage_apply(local_leaves, h):
        def body(h, leaves):
            return sched_block(leaves, h), None

        h, _ = lax.scan(body, h, local_leaves)
        return h

    def spmd_fn(region, xm_all):
        local_stacked = _leaves_of(region)
        stage = lax.axis_index("pp")
        state = jnp.zeros(xm_all.shape[1:], xm_all.dtype)
        out_buf = jnp.zeros_like(xm_all)

        def step(t, carry):
            state_, out_ = carry
            inp = jnp.where(stage == 0, xm_all[jnp.minimum(t, M - 1)], state_)
            h = stage_apply(local_stacked, inp)
            widx = t - (S - 1)
            valid = (stage == S - 1) & (widx >= 0)
            wi = jnp.clip(widx, 0, M - 1)
            old = lax.dynamic_slice_in_dim(out_, wi, 1, 0)[0]
            out_ = lax.dynamic_update_slice_in_dim(
                out_, jnp.where(valid, h, old)[None], wi, 0
            )
            nxt = lax.ppermute(h, "pp", [(i, i + 1) for i in range(S - 1)])
            return nxt, out_

        _, out_buf = lax.fori_loop(0, M + S - 1, step, (state, out_buf))
        # only the last stage holds real outputs; replicate across pp
        # (f32-safe: bf16 psum crashes XLA CPU's AllReducePromotion)
        out_buf = _psum_f32safe(
            jnp.where(stage == S - 1, out_buf, jnp.zeros_like(out_buf)), "pp"
        )
        return out_buf

    def spmd_fn_interleaved(region, xm_all):
        """PHASED interleaved (virtual-pp) schedule: stage s holds V chunks
        (global chunk v*S + s); per step each stage applies exactly ONE chunk
        (1/V of its layers) to one in-flight micro-batch and hands it on with
        ppermute. Micro-batches are processed in groups of S; within a group,
        micro-batch m runs chunk c at group-local step m + c, which is
        conflict-free and keeps every stage busy back-to-back across groups.

        Cost: ceil(M/S)*S*V + S - 1 steps of 1/V layer-cost each — total
        M + (S-1)/V full-stage passes, i.e. the (S-1)-step flush bubble
        shrinks by V, exactly the interleaved-1F1B payoff (reference:
        fleet/meta_parallel interleaved 1F1B; see schedule_info()).
        """
        local_stacked = _leaves_of(region)
        stage = lax.axis_index("pp")
        L_chunk = pipe.num_layers // (S * V)
        # local slot v = global chunk v*S + s (s-major stacking, see __init__)
        local_v = tuple(
            l.reshape((V, L_chunk) + l.shape[1:]) for l in local_stacked
        )
        groups = -(-M // S)
        n_steps = groups * S * V + S - 1
        h0 = jnp.zeros(xm_all.shape[1:], xm_all.dtype)
        out_buf = jnp.zeros_like(xm_all)

        def step(t, carry):
            h_, out_ = carry
            # which (group, slot, micro-batch) is this stage working on?
            rel_total = t - stage
            g = jnp.maximum(rel_total, 0) // (S * V)
            rel = rel_total - g * S * V  # group-local, in [0, S*V) when valid
            k_raw = rel // S  # local virtual slot
            m_local = rel % S
            mb_idx = jnp.clip(g * S + m_local, 0, M - 1)
            valid = (rel_total >= 0) & (g < groups) & (g * S + m_local < M)
            k = jnp.clip(k_raw, 0, V - 1)

            # chunk 0 input is a fresh micro-batch; all others arrive via the
            # ppermute ring (incl. the S-1 -> 0 wrap, which advances the slot)
            inject = valid & (stage == 0) & (k_raw == 0)
            inp = jnp.where(inject, xm_all[mb_idx], h_)
            leaves = tuple(
                lax.dynamic_index_in_dim(l, k, 0, keepdims=False)
                for l in local_v
            )
            o = stage_apply(leaves, inp)

            done = valid & (stage == S - 1) & (k_raw == V - 1)
            old = lax.dynamic_slice_in_dim(out_, mb_idx, 1, 0)[0]
            out_ = lax.dynamic_update_slice_in_dim(
                out_, jnp.where(done, o, old)[None], mb_idx, 0
            )
            h_next = lax.ppermute(o, "pp", [(i, (i + 1) % S) for i in range(S)])
            return h_next, out_

        _, out_buf = lax.fori_loop(0, n_steps, step, (h0, out_buf))
        out_buf = _psum_f32safe(
            jnp.where(stage == S - 1, out_buf, jnp.zeros_like(out_buf)), "pp"
        )
        return out_buf

    def spmd_fn_scheduled(region, xm_all):
        """Explicitly SCHEDULED pipeline (schedule=1f1b / zero_bubble): the
        forward runs the phased chunk ring (same decode as the interleaved
        schedule, for any V>=1) and stashes each chunk's input; a
        jax.custom_vjp replays the ring in REVERSE tick order for the
        backward, so the compiled backward follows the 1F1B tick/slot
        discipline — each backward tick recomputes one chunk from its
        stashed input (inherent "full" remat; only the M x V chunk inputs
        persist per stage) and hands the input-cotangent to the previous
        stage over the reverse ppermute ring.

        zero_bubble additionally splits each backward tick into an
        input-grad-only hop (weights constant under the vjp, so no
        weight-grad math delays the ring) and defers ALL weight-grad work
        to a dense scan after the ring drains — the work that fills the
        drain bubble on real hardware (ZB-H1 decomposition; see
        docs/PIPELINE.md §2). Numerics: identical math to the derived
        path up to reassociation (equivalence pinned <=1e-5 over 3 AdamW
        steps in tests/test_pipeline_schedules.py).
        """
        L_chunk = pipe.num_layers // (S * V)
        groups = -(-M // S)
        n_steps = groups * S * V + S - 1

        def decode(t):
            st = lax.axis_index("pp")
            rel_total = t - st
            g = jnp.maximum(rel_total, 0) // (S * V)
            rel = rel_total - g * S * V
            k_raw = rel // S
            m_local = rel % S
            mb_idx = jnp.clip(g * S + m_local, 0, M - 1)
            valid = (rel_total >= 0) & (g < groups) & (g * S + m_local < M)
            k = jnp.clip(k_raw, 0, V - 1)
            inject = valid & (st == 0) & (k_raw == 0)
            done = valid & (st == S - 1) & (k_raw == V - 1)
            return mb_idx, k, valid, inject, done

        def as_chunks(leaves):
            return tuple(
                l.reshape((V, L_chunk) + l.shape[1:]) for l in leaves)

        def chunk_apply(lv, h):
            def body(h, leaves):
                return sched_block_raw(leaves, h), None

            if train_key is not None:
                with _rng_pp.trace_key_scope(train_key):
                    h, _ = lax.scan(body, h, lv)
            else:
                h, _ = lax.scan(body, h, lv)
            return h

        def fwd_loop(leaves, xm_):
            local_v = as_chunks(leaves)
            h0 = jnp.zeros(xm_.shape[1:], xm_.dtype)
            out0 = jnp.zeros_like(xm_)
            acts0 = jnp.zeros((V * M,) + xm_.shape[1:], xm_.dtype)

            def tick(t, carry):
                h_, out_, acts_ = carry
                mb_idx, k, valid, inject, done = decode(t)
                inp = jnp.where(inject, xm_[mb_idx], h_)
                slot = k * M + mb_idx
                old_a = lax.dynamic_index_in_dim(acts_, slot, 0,
                                                 keepdims=False)
                acts_ = lax.dynamic_update_index_in_dim(
                    acts_, jnp.where(valid, inp, old_a), slot, 0)
                lv = tuple(lax.dynamic_index_in_dim(l, k, 0, keepdims=False)
                           for l in local_v)
                o = chunk_apply(lv, inp)
                old = lax.dynamic_index_in_dim(out_, mb_idx, 0,
                                               keepdims=False)
                out_ = lax.dynamic_update_index_in_dim(
                    out_, jnp.where(done, o, old), mb_idx, 0)
                h_next = lax.ppermute(
                    o, "pp", [(i, (i + 1) % S) for i in range(S)])
                return h_next, out_, acts_

            _, out, acts = lax.fori_loop(0, n_steps, tick, (h0, out0, acts0))
            return out, acts

        @jax.custom_vjp
        def sched(leaves, xm_):
            return fwd_loop(leaves, xm_)[0]

        def sched_fwd(leaves, xm_):
            out, acts = fwd_loop(leaves, xm_)
            return out, (leaves, acts)

        def sched_bwd(res, g_out):
            leaves, acts = res
            local_v = as_chunks(leaves)
            zb = sched_name == "zero_bubble"
            c0 = jnp.zeros(g_out.shape[1:], g_out.dtype)
            gx0 = jnp.zeros_like(g_out)
            wg0 = tuple(jnp.zeros_like(l) for l in local_v)
            cts0 = (jnp.zeros_like(acts) if zb
                    else jnp.zeros((1,), g_out.dtype))

            def tick(tb, carry):
                c_, gx_, wg_, cts_ = carry
                tf = n_steps - 1 - tb
                mb_idx, k, valid, inject, done = decode(tf)
                # the final chunk's output cotangent comes from the loss
                # side; every other tick consumes the ring
                ct = jnp.where(done, g_out[mb_idx], c_)
                slot = k * M + mb_idx
                inp = lax.dynamic_index_in_dim(acts, slot, 0, keepdims=False)
                lv = tuple(lax.dynamic_index_in_dim(l, k, 0, keepdims=False)
                           for l in local_v)
                if zb:
                    _, dgrad = jax.vjp(lambda h_: chunk_apply(lv, h_), inp)
                    (d_inp,) = dgrad(ct)
                    old_c = lax.dynamic_index_in_dim(cts_, slot, 0,
                                                     keepdims=False)
                    cts_ = lax.dynamic_update_index_in_dim(
                        cts_, jnp.where(valid, ct, old_c), slot, 0)
                else:
                    _, vjp_fn = jax.vjp(chunk_apply, lv, inp)
                    d_lv, d_inp = vjp_fn(ct)
                    wg_upd = []
                    for w, dl in zip(wg_, d_lv):
                        cur = lax.dynamic_index_in_dim(w, k, 0,
                                                       keepdims=False)
                        upd = cur + jnp.where(valid, dl, jnp.zeros_like(dl))
                        wg_upd.append(
                            lax.dynamic_update_index_in_dim(w, upd, k, 0))
                    wg_ = tuple(wg_upd)
                d_inp = jnp.where(valid, d_inp, jnp.zeros_like(d_inp))
                old = lax.dynamic_index_in_dim(gx_, mb_idx, 0, keepdims=False)
                gx_ = lax.dynamic_update_index_in_dim(
                    gx_, jnp.where(inject, d_inp, old), mb_idx, 0)
                c_next = lax.ppermute(
                    jnp.where(valid & ~inject, d_inp, jnp.zeros_like(d_inp)),
                    "pp", [(i, (i - 1) % S) for i in range(S)])
                return c_next, gx_, wg_, cts_

            _, gx, wg, cts = lax.fori_loop(
                0, n_steps, tick, (c0, gx0, wg0, cts0))

            if zb:
                # deferred weight-grad: dense scan over the stashed
                # (input, cotangent) pairs of each local chunk slot.
                # Invalid slots hold zero cotangents -> zero contribution.
                acts_v = acts.reshape((V, M) + acts.shape[1:])
                cts_v = cts.reshape((V, M) + cts.shape[1:])
                per_k = []
                for k in range(V):
                    lv = tuple(l[k] for l in local_v)

                    def body(acc, pair, lv=lv):
                        inp, ct = pair
                        _, wjp = jax.vjp(
                            lambda lv_: chunk_apply(lv_, inp), lv)
                        (d_lv,) = wjp(ct)
                        return tuple(a + d for a, d in zip(acc, d_lv)), None

                    acc0 = tuple(jnp.zeros_like(l) for l in lv)
                    acc, _ = lax.scan(body, acc0, (acts_v[k], cts_v[k]))
                    per_k.append(acc)
                wg = tuple(
                    jnp.stack([per_k[k][j] for k in range(V)], 0)
                    for j in range(len(local_v)))
            d_leaves = tuple(
                w.reshape((V * L_chunk,) + w.shape[2:]) for w in wg)
            return d_leaves, gx

        sched.defvjp(sched_fwd, sched_bwd)

        local_stacked = _leaves_of(region)
        out_buf = sched(tuple(local_stacked), xm_all)
        stage = lax.axis_index("pp")
        return _psum_f32safe(
            jnp.where(stage == S - 1, out_buf, jnp.zeros_like(out_buf)), "pp")

    if sched_name != "gpipe":
        spmd_fn = spmd_fn_scheduled
    elif V > 1:
        spmd_fn = spmd_fn_interleaved

    # pp_* telemetry (single writer: this module — scripts/
    # check_observability.py OWNED_PREFIXES): compiled-schedule shape and
    # the comm volume the bucket structure lets backward hide. Trace-time
    # statics, mirroring grad_comm.record_build_stats.
    t_sched = time.perf_counter()
    info = pipe.schedule_info(B, schedule=sched_name)
    _obs.set_gauge("pp_schedule_ticks", float(info["schedule_ticks"]))
    _obs.set_gauge("pp_bubble_fraction",
                   float(info["measured_bubble_fraction"]))
    hidden_bytes = 0
    if bucket_layouts:
        wire_it = cfg.wire_itemsize if cfg.quantized else 4
        hidden_bytes = pipe.num_layers * (
            sum(l.total for l in bucket_layouts)
            - bucket_layouts[0].total) * wire_it
    _obs.set_gauge("pp_overlap_hidden_bytes", float(hidden_bytes))
    # host-side schedule-build span: the per-tick device time runs inside
    # the single compiled SPMD program, so the attrs (tick grid, bubble
    # fraction) are the trace-visible shape of the window
    _obs.record_span("pp_tick_window",
                     dur_s=time.perf_counter() - t_sched,
                     schedule=sched_name,
                     ticks=int(info["schedule_ticks"]),
                     bubble_fraction=float(info["measured_bubble_fraction"]))

    # On the CPU backend, sub-f32 i/o crosses the shard_map boundary as
    # f32: the replicated input's cotangent is a jax-inserted psum at this
    # boundary, and XLA CPU's AllReducePromotion CHECK-fails on the
    # copy-rooted reduction region jax emits for bf16 psums (see
    # collective._promote_subf32_reduce). The converts fuse; compute
    # inside stays in the model dtype; TPU keeps native-dtype i/o.
    from ...collective import _promote_subf32_reduce

    promote = _promote_subf32_reduce(x.dtype)
    inner_fn = spmd_fn
    if promote:
        def spmd_fn(region, xm_all):  # noqa: F811
            return inner_fn(
                region, xm_all.astype(x.dtype)).astype(jnp.float32)

    from ...._jax_compat import shard_map as _shard_map

    region_axes = frozenset({"pp"}) | frozenset(bs_axes) | (
        frozenset({"sharding"}) if z_layout is not None else frozenset())
    mapped = _shard_map(
        spmd_fn,
        mesh=m,
        in_specs=(tuple(region_specs), data_spec),
        out_specs=data_spec,
        axis_names=region_axes,
        check_vma=False,
    )
    # jit wrapper: the partial-manual shard_map eager impl path is broken in
    # current jax (nested unmatch uses the full axis set); the traced path is
    # fine, and under an outer jit this inlines.
    out = jax.jit(mapped)(
        tuple(region_vals), xm.astype(jnp.float32) if promote else xm)
    out = out.astype(x.dtype)
    if bs_axes:
        # inverse of the row interleave: out[t, j] is batch row j*M + t
        out = out.swapaxes(0, 1)
    return out.reshape((B,) + out.shape[2:])


class PipelineLayer(Layer):
    """paddle PipelineLayer parity: LayerDesc list + segmentation."""

    def __init__(
        self,
        layers: Sequence,
        num_stages: Optional[int] = None,
        topology=None,
        loss_fn: Optional[Callable] = None,
        seg_method: str = "uniform",
        recompute_interval: int = 0,
        recompute_granularity: str = "full",
        num_virtual_pipeline_stages: Optional[int] = None,
        **kwargs,
    ):
        super().__init__()
        self._loss_fn = loss_fn
        self.num_stages = num_stages or max(_mesh.mesh_axis_size("pp"), 1)
        built: List[Layer] = []
        self._shared = {}
        for d in layers:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    layer = self._shared[d.layer_name]
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                if d.forward_func is not None:
                    layer = _ForwardWrapper(layer, d.forward_func)
                built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FnLayer(d))
            else:
                raise TypeError(f"unsupported pipeline item {d!r}")
        # find the longest homogeneous run to fold into SpmdPipeline
        runs = []
        i = 0
        while i < len(built):
            j = i
            if list(built[i].named_parameters()):
                sig = (type(built[i]), _param_sig(built[i]))
                while j + 1 < len(built) and isinstance(built[j + 1], type(built[i])) and (
                    type(built[j + 1]),
                    _param_sig(built[j + 1]),
                ) == sig:
                    j += 1
            runs.append((i, j))
            i = j + 1
        # fold EVERY homogeneous run long enough to stage-shard into its own
        # SpmdPipeline — heterogeneous pipelines (e.g. a conv stem run + a
        # transformer body run) get each body partitioned; non-foldable
        # layers between runs execute replicated (cheap under SPMD)
        self._segments: List[Layer] = []
        n_virtual_req = max(num_virtual_pipeline_stages or 1, 1)
        folded_any = False
        for lo, hi in runs:
            n_run = hi - lo + 1
            n_virtual = n_virtual_req
            n_chunks = self.num_stages * n_virtual
            if n_virtual > 1 and (n_run < n_chunks or n_run % n_chunks != 0)                     and n_run % self.num_stages == 0:
                # virtual stages don't divide this run — fall back to V=1
                # rather than silently disabling pipelining altogether
                warnings.warn(
                    f"num_virtual_pipeline_stages={n_virtual} does not "
                    f"divide the {n_run}-block run over "
                    f"{self.num_stages} stages; falling back to "
                    "non-interleaved pipeline for this run"
                )
                n_virtual = 1
                n_chunks = self.num_stages
            if self.num_stages > 1 and n_run >= n_chunks                     and n_run % n_chunks == 0:
                self._segments.append(
                    SpmdPipeline(
                        built[lo : hi + 1],
                        num_stages=self.num_stages,
                        recompute_block=recompute_interval > 0,
                        recompute_granularity=recompute_granularity,
                        num_virtual_stages=n_virtual,
                    )
                )
                folded_any = True
            else:
                self._segments.extend(built[lo : hi + 1])
        if self.num_stages > 1 and not folded_any:
            warnings.warn(
                f"no homogeneous layer run divides {self.num_stages} "
                "pipeline stages; the model runs WITHOUT pipeline "
                "partitioning"
            )
        for i, l in enumerate(self._segments):
            self.add_sublayer(f"seg_{i}", l)

    def forward(self, x):
        for l in self._segments:
            x = l(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *a, **k):
        return self._fn(*a, **k)


class _ForwardWrapper(Layer):
    def __init__(self, layer, fn):
        super().__init__()
        self.inner = layer
        self._fn = fn

    def forward(self, *a, **k):
        return self._fn(self.inner, *a, **k)


class PipelineParallel(Layer):
    """fleet.meta_parallel.PipelineParallel parity: the train_batch driver."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._step_cache = {}

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One pipelined training step — compiled end to end (forward over all
        micro-batches + backward + update in a single XLA program)."""
        from .. import DistTrainStep

        x, y = data
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if loss_fn is None:
            raise ValueError("PipelineLayer needs loss_fn for train_batch")
        key = id(optimizer)
        step = self._step_cache.get(key)
        if step is None:

            def compute_loss(model, xb, yb):
                out = model(xb)
                return loss_fn(out, yb)

            step = DistTrainStep(self._layers, compute_loss, optimizer)
            self._step_cache[key] = step
        loss = step(x, y)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if compute_loss and loss_fn is not None:
            return loss_fn(out, y)
        return out

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
