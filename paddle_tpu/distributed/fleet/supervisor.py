"""Fleet supervisor: the train/serve colocation control loop.

Closes the loop the MPMD + live-telemetry PRs built toward
(docs/COLOCATION.md): a control daemon that owns the fleet inventory —
which workers are serving engines and which are MPMD training stage
capacity — reads the live ``fleet_health.json`` (per-SLO-class
error-budget burn rates + the router's admission-queue and
outstanding-token gauges), prices each candidate role flip via
``planner.plan_mpmd_stages``, and executes flips as **two-phase,
journaled, crash-recoverable transactions**.

Flip state machine (one named fence per transition, in order)::

    plan -> drain -> quiesce -> resize -> commit -> finalize

Every fence is journaled to an atomic on-disk flip log BEFORE the
fence's action runs (tmp + ``os.replace``, the checkpoint-writer
discipline), and ``chaos.flip_fence(name)`` fires right after the
journal write — so a supervisor SIGKILL at ANY fence leaves the journal
durably recording exactly how far the transaction got. Recovery reads
it on startup and restores a consistent fleet:

* fence **before** ``commit`` — roll BACK: the executor undoes whatever
  partial work the recorded fence implies (drain orders lifted, resize
  restored from the journaled source width) and the durable roles doc
  stays at the source assignment. No half-flipped worker is ever left
  serving a stale role.
* fence **at/after** ``commit`` — roll FORWARD: the target roles doc is
  (re)written and the executor's ``activate`` re-runs (idempotent by
  contract); the flip counts as committed.

Control-loop robustness: a flip needs its trigger signal to HOLD for
``hysteresis_s`` (one hot pump cannot thrash the fleet), committed flips
are spaced by ``cooldown_s``, and a flip-storm circuit breaker opens
when more than ``breaker_max_flips`` commit inside ``breaker_window_s``
— while open, the supervisor only observes. Breaker state is persisted
in the roles doc so the dashboard (scripts/fleet_dashboard.py) and a
relaunched supervisor both see it.

The store/transport side effects live behind the ``FlipExecutor``
interface so the state machine is testable without a fleet;
``StoreFleetExecutor`` is the real one — drain orders through the
per-engine ctl key (serving/protocol.py), in-flight handoff through
``Router.evacuate`` (the PR 9 failover resubmit path, bit-equal reruns
by explicit seeds), training resize through a caller-supplied hook
(``ElasticManager.live_resize`` / ``MpmdPipeline.resize_stage``).

This module is the single writer of the ``supervisor_*`` metric family
and the ``flip`` span (scripts/check_observability.py), every store op
sits under ``deadline_guard`` and every journal write goes through the
one atomic chokepoint ``_atomic_write_json`` (check_robustness.py
rule 8).
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ... import observability as _obs
from ...serving.protocol import (DEFAULT_NAMESPACE, deadline_guard,
                                 k_ctl_engine, k_occ, pack, unpack)
from ...testing import chaos

__all__ = ["FENCES", "WEIGHT_FENCES", "FlipExecutor", "FlipJournal",
           "FleetSupervisor", "StoreFleetExecutor", "SupervisorConfig",
           "read_health"]

#: ordered flip-transition fences; ``commit`` is the durability point —
#: recovery rolls forward at/after it and back before it
FENCES = ("plan", "drain", "quiesce", "resize", "commit", "finalize")
COMMIT_INDEX = FENCES.index("commit")

#: ordered fences of the online WEIGHT-epoch transaction (the journal's
#: second transaction type, serving/online.py): ``commit`` is journaled
#: BEFORE the engine pointer-swaps, so roll-forward recovery re-sends
#: the idempotent swap orders (engines at/past the epoch no-op) and
#: roll-back discards shadow buffers that were never promoted
WEIGHT_FENCES = ("publish", "stream", "commit", "swap", "finalize")
WEIGHT_COMMIT_INDEX = WEIGHT_FENCES.index("commit")

#: committed/rolled-back flips kept in the journal's history log
_HISTORY_CAP = 64


def _atomic_write_json(path: str, doc: dict) -> None:
    """The ONE journal/roles write chokepoint: serialize, write to a tmp
    sibling, fsync, ``os.replace``. A SIGKILL at any instant leaves
    either the old doc or the new one on disk — never a torn file.
    check_robustness.py rule 8 statically confines every write-mode
    ``open`` in this module to this function."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    """Read a journal/roles/health doc; None when absent or torn (a torn
    doc can only be a crashed FOREIGN writer — ours are atomic)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_health(path: str) -> dict:
    """Load ``fleet_health.json`` (observability/live.py schema); an
    absent/torn doc reads as empty — the supervisor then simply holds
    (no signal is never a reason to flip)."""
    return _read_json(path) or {}


@dataclass
class SupervisorConfig:
    #: any class's burn rate at/above this (or an admission backlog at/
    #: above queue_high) is serving pressure -> flip capacity TO serving
    high_burn: float = 1.0
    #: every burn rate at/below this with empty admission queues is
    #: serving headroom -> flip an idle engine TO training
    low_burn: float = 0.5
    #: admitted-but-undispatched requests (all classes) that count as
    #: serving pressure even before latency burn shows it
    queue_high: int = 8
    #: the trigger signal must hold this long before a flip fires
    hysteresis_s: float = 2.0
    #: minimum spacing between committed flips
    cooldown_s: float = 5.0
    #: flip-storm circuit breaker: more than breaker_max_flips commits
    #: inside breaker_window_s opens the breaker for breaker_open_s
    breaker_window_s: float = 60.0
    breaker_max_flips: int = 4
    breaker_open_s: float = 30.0
    #: serving engines the supervisor must always leave in place
    min_serving: int = 1
    #: seconds a drain may take before in-flight work is handed off via
    #: the router's evacuate (failover resubmit) path
    drain_timeout_s: float = 30.0
    #: pricing: growing training by one worker must be predicted to cut
    #: the step time by at least this fraction, or the flip is skipped
    #: (diminishing-returns guard; serving-pressure flips always clear)
    min_speedup: float = 0.02
    #: MPMD stage count + boundary wire dtype handed to the planner
    plan_stages: int = 2
    wire: str = "f32"
    #: serving namespace for the store-side executor
    namespace: str = DEFAULT_NAMESPACE


@dataclass
class FlipDecision:
    direction: str                  # to_training | to_serving
    engine: str                     # worker being flipped
    reason: str                     # trigger that held through hysteresis
    price: dict = field(default_factory=dict)


class FlipJournal:
    """Atomic on-disk flip log + durable fleet roles doc.

    Layout under ``root``::

        fleet_roles.json   durable truth: {"roles": {name: role},
                           "training_width": int, "breaker_open_until":
                           wall ts or 0, "flips_committed": int}
        flip_current.json  the in-flight flip transaction (absent when
                           no flip is in flight); rewritten atomically
                           at every fence
        flip_log.json      bounded history of closed flips, newest last
        weights_current.json  the in-flight online weight-epoch
                           transaction (serving/online.py), same
                           fence-before-action protocol over
                           WEIGHT_FENCES
        weight_log.json    bounded history of closed weight flips

    One flip is in flight at a time — the supervisor serializes role
    changes, which is what makes single-doc recovery sufficient. The
    weight transaction is serialized the same way (one epoch publishes
    at a time) and shares the atomic-write chokepoint.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.roles_path = os.path.join(root, "fleet_roles.json")
        self.current_path = os.path.join(root, "flip_current.json")
        self.history_path = os.path.join(root, "flip_log.json")
        self.weights_path = os.path.join(root, "weights_current.json")
        self.weight_history_path = os.path.join(root, "weight_log.json")

    # -- roles doc -----------------------------------------------------------

    def load_roles(self) -> Optional[dict]:
        return _read_json(self.roles_path)

    def save_roles(self, doc: dict) -> None:
        _atomic_write_json(self.roles_path, doc)

    # -- the in-flight flip --------------------------------------------------

    def pending(self) -> Optional[dict]:
        return _read_json(self.current_path)

    def begin(self, doc: dict) -> None:
        doc["fence"] = FENCES[0]
        doc["fences"] = {FENCES[0]: time.time()}
        _atomic_write_json(self.current_path, doc)

    def advance(self, doc: dict, fence: str) -> None:
        if fence not in FENCES:
            raise ValueError(f"unknown flip fence {fence!r}")
        doc["fence"] = fence
        doc["fences"][fence] = time.time()
        _atomic_write_json(self.current_path, doc)

    def close(self, doc: dict, outcome: str) -> None:
        """Retire the in-flight flip into the bounded history log, THEN
        drop the current doc — a kill between the two writes leaves a
        closed flip still pending, and re-closing it is idempotent."""
        entry = {k: doc.get(k) for k in
                 ("id", "direction", "engine", "reason", "fence", "fences",
                  "source_width", "target_width", "price")}
        entry["outcome"] = outcome
        entry["closed_ts"] = time.time()
        history = _read_json(self.history_path) or []
        history = [h for h in history if h.get("id") != entry["id"]]
        history.append(entry)
        _atomic_write_json(self.history_path, history[-_HISTORY_CAP:])
        try:
            os.remove(self.current_path)
        except OSError:
            pass

    def history(self) -> List[dict]:
        return _read_json(self.history_path) or []

    # -- the in-flight weight-epoch transaction (serving/online.py) ---------

    def pending_weights(self) -> Optional[dict]:
        return _read_json(self.weights_path)

    def begin_weights(self, doc: dict) -> None:
        doc["fence"] = WEIGHT_FENCES[0]
        doc["fences"] = {WEIGHT_FENCES[0]: time.time()}
        _atomic_write_json(self.weights_path, doc)

    def advance_weights(self, doc: dict, fence: str) -> None:
        if fence not in WEIGHT_FENCES:
            raise ValueError(f"unknown weight fence {fence!r}")
        doc["fence"] = fence
        doc["fences"][fence] = time.time()
        _atomic_write_json(self.weights_path, doc)

    def close_weights(self, doc: dict, outcome: str) -> None:
        """Retire the in-flight weight flip into its history log, THEN
        drop the current doc (same idempotent two-write order as
        ``close``)."""
        entry = {k: doc.get(k) for k in
                 ("id", "epoch", "engines", "fence", "fences", "leaves",
                  "wire", "bytes", "acked")}
        entry["outcome"] = outcome
        entry["closed_ts"] = time.time()
        history = _read_json(self.weight_history_path) or []
        history = [h for h in history if h.get("id") != entry["id"]]
        history.append(entry)
        _atomic_write_json(self.weight_history_path,
                           history[-_HISTORY_CAP:])
        try:
            os.remove(self.weights_path)
        except OSError:
            pass

    def weight_history(self) -> List[dict]:
        return _read_json(self.weight_history_path) or []


class FlipExecutor:
    """Side-effect interface of the flip state machine. The base class
    is a no-op fleet (unit tests subclass it to record/raise); the
    methods are the per-fence actions, each invoked AFTER its fence is
    journaled. ``activate`` and ``rollback`` must be idempotent — crash
    recovery may re-run them."""

    def drain(self, engine: str, deadline_s: float) -> bool:
        """Stop ``engine`` admitting new work; return True once its
        in-flight requests finished, False if the deadline expired and
        leftovers were handed off (failover resubmit path)."""
        return True

    def quiesce(self, engine: str) -> None:
        """``engine`` is drained: release its devices for their new
        role (nothing may still be running on them after this)."""

    def resize(self, source_width: int, target_width: int) -> None:
        """Grow/shrink the training side (live_resize/resize_stage)."""

    def activate(self, engine: str, role: str) -> None:
        """Bring ``engine`` up in its committed role (idempotent)."""

    def rollback(self, doc: dict) -> None:
        """Undo a pre-commit partial flip described by the journal doc
        (idempotent): lift drain orders, restore the source width."""


class StoreFleetExecutor(FlipExecutor):
    """The real executor: drain orders through the per-engine ctl key,
    drain progress watched via the engine's occupancy beat, leftover
    in-flight work handed off through ``router.evacuate`` (bit-equal
    reruns — request seeds are router-assigned), training resize through
    a caller-supplied hook. ``pump`` (optional) is called while waiting
    so an in-process router keeps making progress."""

    def __init__(self, store, *, namespace: str = DEFAULT_NAMESPACE,
                 router=None, resize_fn: Optional[Callable] = None,
                 pump: Optional[Callable] = None, poll_s: float = 0.02):
        self._store = store
        self._ns = namespace
        self._router = router
        self._resize_fn = resize_fn
        self._pump = pump
        self._poll_s = poll_s

    def _order(self, engine: str, drain: bool) -> None:
        with deadline_guard("supervisor drain order"):
            self._store.set(k_ctl_engine(self._ns, engine),
                            pack({"drain": drain, "ts": time.time()}))

    def drain(self, engine: str, deadline_s: float) -> bool:
        self._order(engine, True)
        deadline = time.monotonic() + deadline_s
        key = k_occ(self._ns, engine)
        clean = False
        while time.monotonic() < deadline:
            if self._pump is not None:
                self._pump()
            with deadline_guard("supervisor poll drain"):
                have = self._store.check(key)
                occ = unpack(self._store.get(key)) if have else {}
            if occ.get("drained"):
                clean = True
                break
            time.sleep(self._poll_s)
        if self._router is not None:
            # hand the book back even after a CLEAN drain: the worker
            # stops consuming dispatch seqs on the drain edge, so
            # dispatched-but-never-admitted requests are stranded on its
            # queue — evacuate harvests the finished rids and requeues
            # the rest (bit-equal reruns, router-assigned seeds)
            self._router.evacuate(engine)
        return clean

    def resize(self, source_width: int, target_width: int) -> None:
        if self._resize_fn is not None:
            self._resize_fn(source_width, target_width)

    def activate(self, engine: str, role: str) -> None:
        # a serving engine resumes admission; a training worker keeps
        # its drain order so it never re-admits behind the fleet's back
        self._order(engine, drain=(role != "serving"))

    def rollback(self, doc: dict) -> None:
        engine = doc.get("engine")
        if engine:
            src_role = doc.get("source_roles", {}).get(engine, "serving")
            self._order(engine, drain=(src_role != "serving"))
        if self._resize_fn is not None and doc.get("resized"):
            self._resize_fn(doc.get("target_width"),
                            doc.get("source_width"))


class FleetSupervisor:
    """Own the fleet inventory and close the SLO control loop.

    ``tick()`` is the loop body: read the health doc, hold the trigger
    through hysteresis/cooldown/breaker, price the flip, execute it as
    a journaled transaction. Construction runs ``recover()`` first, so
    a relaunched supervisor always starts from a consistent fleet.
    """

    def __init__(self, journal_dir: str, *,
                 executor: Optional[FlipExecutor] = None,
                 config: Optional[SupervisorConfig] = None,
                 health_path: Optional[str] = None,
                 roles: Optional[Dict[str, str]] = None,
                 training_width: int = 0):
        self.config = config or SupervisorConfig()
        self.executor = executor or FlipExecutor()
        self.journal = FlipJournal(journal_dir)
        self.health_path = health_path
        self._pressure_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_commit_t = -float("inf")
        self._commit_times: List[float] = []
        self._next_flip_id = int(time.time())
        self.last_outcome: Optional[str] = None
        self.recover()
        if self.journal.load_roles() is None:
            self.journal.save_roles({
                "roles": dict(roles or {}),
                "training_width": int(training_width),
                "breaker_open_until": 0.0,
                "flips_committed": 0,
            })
        self._export_role_gauges()

    # -- inventory -----------------------------------------------------------

    @property
    def roles_doc(self) -> dict:
        return self.journal.load_roles() or {
            "roles": {}, "training_width": 0,
            "breaker_open_until": 0.0, "flips_committed": 0}

    def _count(self, doc: dict, role: str) -> int:
        return sum(1 for r in doc["roles"].values() if r == role)

    def _export_role_gauges(self) -> None:
        doc = self.roles_doc
        for role in ("serving", "training"):
            _obs.set_gauge("supervisor_fleet_roles",
                           self._count(doc, role), role=role)
        _obs.set_gauge(
            "supervisor_breaker_open",
            1.0 if doc.get("breaker_open_until", 0) > time.time() else 0.0)

    # -- crash recovery ------------------------------------------------------

    def recover(self) -> Optional[str]:
        """Resolve a flip the previous supervisor left in flight. Rolls
        forward at/after the commit fence, back before it; returns the
        outcome ("rolled_forward" | "rolled_back") or None."""
        doc = self.journal.pending()
        if doc is None:
            return None
        fence = doc.get("fence", FENCES[0])
        idx = FENCES.index(fence) if fence in FENCES else 0
        if idx >= COMMIT_INDEX:
            # committed: finish the flip — target roles are the truth
            self.journal.save_roles(doc["target_roles_doc"])
            self.executor.activate(doc["engine"], doc["target_role"])
            self.journal.close(doc, "rolled_forward")
            _obs.inc("supervisor_flips_total", direction=doc["direction"])
            _obs.event("flip_commit", id=doc["id"],
                       direction=doc["direction"], engine=doc["engine"],
                       recovered=True, fence=fence)
            outcome = "rolled_forward"
        else:
            # not committed: the source assignment stays the truth
            self.executor.rollback(doc)
            self.journal.save_roles(doc["source_roles_doc"])
            self.journal.close(doc, "rolled_back")
            _obs.inc("supervisor_rollbacks_total")
            _obs.event("flip_rollback", id=doc["id"],
                       direction=doc["direction"], engine=doc["engine"],
                       recovered=True, fence=fence)
            outcome = "rolled_back"
        self.last_outcome = outcome
        self._export_role_gauges()
        return outcome

    # -- pricing -------------------------------------------------------------

    def price(self, direction: str) -> dict:
        """Price the candidate flip with the MPMD stage planner: the
        predicted training step time at the current vs the flipped
        width (boundary bytes at the resolved wire dtype ride along so
        the journal records WHY). A training side of width 0 prices as
        idle (any growth approves; nothing to shrink-price)."""
        doc = self.roles_doc
        width = int(doc.get("training_width", 0))
        target = width + 1 if direction == "to_training" else width - 1
        out = {"source_width": width, "target_width": target,
               "approve": True}

        def _step_s(w: int) -> Optional[dict]:
            if w < 1:
                return None
            from ..auto_parallel.planner import (Topology,
                                                 plan_mpmd_stages)
            stages = max(1, min(self.config.plan_stages, w))
            plan = plan_mpmd_stages(
                topology=Topology(n_devices=w), num_stages=stages,
                wire=self.config.wire)
            return {"predicted_step_s": plan.best.predicted_step_s,
                    "widths": list(plan.best.widths),
                    "boundary_bytes": plan.best.boundary_bytes,
                    "plan_seconds": plan.plan_seconds}

        try:
            out["source"] = _step_s(width)
            out["target"] = _step_s(target)
        except Exception as e:  # planner missing calibration etc.
            out["error"] = str(e)
            return out
        if direction == "to_training" and out["source"] and out["target"]:
            old = out["source"]["predicted_step_s"]
            new = out["target"]["predicted_step_s"]
            out["speedup"] = old / new if new > 0 else float("inf")
            out["approve"] = out["speedup"] >= 1.0 + self.config.min_speedup
        return out

    # -- decision ------------------------------------------------------------

    @staticmethod
    def _signals(health: dict) -> dict:
        """Collapse a fleet_health.json doc to the two control inputs:
        the worst burn rate across classes/objectives and the total
        admission backlog."""
        burn = 0.0
        for cls in (health.get("classes") or {}).values():
            obj = cls.get("objectives") or {}
            for k in ("burn_rate_latency", "burn_rate_availability"):
                if obj.get(k) is not None:
                    burn = max(burn, float(obj[k]))
        queues = health.get("queues") or {}
        admission = queues.get("admission") or {}
        backlog = sum(int(v) for v in admission.values()) \
            if isinstance(admission, dict) else int(admission or 0)
        return {"max_burn": burn, "admission_backlog": backlog}

    def decide(self, health: dict, now: float) -> Optional[FlipDecision]:
        """Hysteresis + cooldown + breaker gate around the raw signals;
        returns the flip to execute, or None to hold."""
        doc = self.roles_doc
        sig = self._signals(health)
        pressure = (sig["max_burn"] >= self.config.high_burn
                    or sig["admission_backlog"] >= self.config.queue_high)
        idle = (sig["max_burn"] <= self.config.low_burn
                and sig["admission_backlog"] == 0)
        self._pressure_since = (self._pressure_since or now) if pressure \
            else None
        self._idle_since = (self._idle_since or now) if idle else None
        if doc.get("breaker_open_until", 0) > time.time():
            return None
        if now - self._last_commit_t < self.config.cooldown_s:
            return None
        held = self.config.hysteresis_s
        if (pressure and now - self._pressure_since >= held
                and self._count(doc, "training") > 0):
            engine = sorted(n for n, r in doc["roles"].items()
                            if r == "training")[0]
            return FlipDecision(
                "to_serving", engine,
                f"burn={sig['max_burn']:.2f} "
                f"backlog={sig['admission_backlog']}",
                self.price("to_serving"))
        if (idle and now - self._idle_since >= held
                and self._count(doc, "serving") > self.config.min_serving):
            engine = sorted(n for n, r in doc["roles"].items()
                            if r == "serving")[-1]
            price = self.price("to_training")
            if not price.get("approve", False):
                return None
            return FlipDecision(
                "to_training", engine,
                f"burn={sig['max_burn']:.2f} idle", price)
        return None

    # -- the transaction -----------------------------------------------------

    def flip(self, decision: FlipDecision, now: Optional[float] = None) \
            -> str:
        """Execute one role flip as the journaled two-phase transaction.
        Returns "committed" or "rolled_back". Any executor failure
        before the commit fence rolls back; chaos SIGKILLs are resolved
        by ``recover()`` on the next launch."""
        now = time.monotonic() if now is None else now
        src_doc = self.roles_doc
        target_role = ("training" if decision.direction == "to_training"
                       else "serving")
        source_role = src_doc["roles"].get(decision.engine, "serving")
        tgt_doc = json.loads(json.dumps(src_doc))
        tgt_doc["roles"][decision.engine] = target_role
        delta = 1 if decision.direction == "to_training" else -1
        tgt_doc["training_width"] = max(
            0, int(src_doc.get("training_width", 0)) + delta)
        tgt_doc["flips_committed"] = \
            int(src_doc.get("flips_committed", 0)) + 1
        doc = {
            "id": self._next_flip_id,
            "direction": decision.direction,
            "engine": decision.engine,
            "reason": decision.reason,
            "price": decision.price,
            "source_role": source_role,
            "target_role": target_role,
            "source_roles": dict(src_doc["roles"]),
            "source_width": int(src_doc.get("training_width", 0)),
            "target_width": int(tgt_doc["training_width"]),
            "source_roles_doc": src_doc,
            "target_roles_doc": tgt_doc,
            "resized": False,
        }
        self._next_flip_id += 1
        t0 = time.perf_counter()
        handle = _obs.start_span("flip", direction=decision.direction,
                                 engine=decision.engine, id=doc["id"])
        self.journal.begin(doc)
        chaos.flip_fence("plan")
        try:
            self.journal.advance(doc, "drain")
            chaos.flip_fence("drain")
            if decision.direction == "to_training":
                doc["drained_clean"] = self.executor.drain(
                    decision.engine, self.config.drain_timeout_s)
            self.journal.advance(doc, "quiesce")
            chaos.flip_fence("quiesce")
            self.executor.quiesce(decision.engine)
            self.journal.advance(doc, "resize")
            chaos.flip_fence("resize")
            self.executor.resize(doc["source_width"], doc["target_width"])
            doc["resized"] = True
        except Exception as e:
            doc["error"] = str(e)
            self.executor.rollback(doc)
            self.journal.save_roles(doc["source_roles_doc"])
            self.journal.close(doc, "rolled_back")
            _obs.inc("supervisor_rollbacks_total")
            _obs.event("flip_rollback", id=doc["id"],
                       direction=decision.direction,
                       engine=decision.engine, fence=doc["fence"],
                       error=str(e))
            _obs.end_span(handle, outcome="rolled_back")
            self.last_outcome = "rolled_back"
            self._export_role_gauges()
            return "rolled_back"
        # COMMIT POINT: once the journal records this fence, recovery
        # rolls forward — the target assignment is the durable truth
        self.journal.advance(doc, "commit")
        chaos.flip_fence("commit")
        self.journal.save_roles(doc["target_roles_doc"])
        self.journal.advance(doc, "finalize")
        chaos.flip_fence("finalize")
        self.executor.activate(decision.engine, target_role)
        self.journal.close(doc, "committed")
        self._last_commit_t = now
        self._commit_times.append(now)
        self._pressure_since = None
        self._idle_since = None
        _obs.inc("supervisor_flips_total", direction=decision.direction)
        _obs.observe("supervisor_flip_duration_seconds",
                     time.perf_counter() - t0)
        _obs.event("flip_commit", id=doc["id"],
                   direction=decision.direction, engine=decision.engine,
                   reason=decision.reason,
                   drained_clean=doc.get("drained_clean"),
                   source_width=doc["source_width"],
                   target_width=doc["target_width"])
        _obs.end_span(handle, outcome="committed")
        self.last_outcome = "committed"
        self._check_breaker(now)
        self._export_role_gauges()
        return "committed"

    def _check_breaker(self, now: float) -> None:
        w = self.config.breaker_window_s
        self._commit_times = [t for t in self._commit_times
                              if now - t <= w]
        if len(self._commit_times) > self.config.breaker_max_flips:
            doc = self.roles_doc
            doc["breaker_open_until"] = \
                time.time() + self.config.breaker_open_s
            self.journal.save_roles(doc)
            _obs.event("supervisor_breaker", state="open",
                       flips_in_window=len(self._commit_times),
                       window_s=w, open_s=self.config.breaker_open_s)

    # -- the loop body -------------------------------------------------------

    def tick(self, health: Optional[dict] = None,
             now: Optional[float] = None) -> Optional[str]:
        """One control-loop round: signals -> decision -> transaction.
        ``health``/``now`` are injectable for deterministic tests; the
        default reads ``health_path`` and the monotonic clock. Returns
        the flip outcome or None when holding."""
        if health is None:
            health = read_health(self.health_path) \
                if self.health_path else {}
        now = time.monotonic() if now is None else now
        decision = self.decide(health, now)
        self._export_role_gauges()
        if decision is None:
            return None
        return self.flip(decision, now)
