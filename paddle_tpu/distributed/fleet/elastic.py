"""Elastic / fault-tolerant training (fleet.elastic parity, TPU-shaped).

Reference capability (SURVEY.md §5 "Failure detection/elastic"): etcd-backed
`ElasticManager` — node registry, heartbeat watch, scale events trigger
re-rendezvous and relaunch; training resumes from the last checkpoint.

TPU-native design (SURVEY.md §7 "Hard parts — Elastic"): TPU slices fail as
a UNIT, so elasticity is not rank replacement but **fast checkpoint-resume**:
a `CheckpointManager`-style loop (orbax-backed, async, sharded) snapshots
every N steps with bounded retention; on restart — same or different
topology — `latest_step()` + `restore()` re-shards onto the live mesh and
training continues. The etcd membership machinery has no analogue to port:
membership is the job scheduler's concern (GKE/Borg restart the slice).

Crash-safety guarantees (docs/FAULT_TOLERANCE.md):
  * saves commit atomically (body -> checksum manifest -> rename), so a
    kill -9 mid-save never leaves a restorable-looking torn `step_N/`;
  * `resume()` restores the newest checkpoint that passes manifest
    verification, skipping torn or corrupted ones with a diagnosis;
  * retention pruning counts only committed checkpoints and never deletes
    the newest one, whatever `max_to_keep` says;
  * a pending async save is flushed before the next save starts and at
    interpreter exit, so back-to-back saves cannot interleave writes.
"""
from __future__ import annotations

import atexit
import os
import shutil
import sys
from typing import Dict, Optional, Tuple

from ... import observability as _obs
from ..checkpoint import (
    TMP_SUFFIX,
    is_complete_checkpoint,
    save_state_dict,
    verify_checkpoint,
)


class ElasticManager:
    """Checkpoint-resume driver. API kept close to a paddle training loop:

        elastic = ElasticManager(ckpt_dir, save_interval=100)
        start = elastic.resume(model, optimizer)          # 0 if fresh
        for step in range(start, total):
            loss = train_step(...)
            elastic.maybe_save(step, model, optimizer)
    """

    def __init__(self, ckpt_dir: str, save_interval: int = 100, max_to_keep: int = 3,
                 async_save: bool = False, verify_on_resume: bool = True):
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        self.save_interval = max(1, int(save_interval))
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.verify_on_resume = verify_on_resume
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self._pending = None
        # a pending async save left in flight at interpreter exit would
        # silently lose the newest checkpoint (and can interleave with a
        # final sync save); commit it on the way out
        atexit.register(self._atexit_flush)

    # -- discovery ----------------------------------------------------------
    def _step_dirs(self) -> Dict[int, str]:
        out = {}
        for name in os.listdir(self.ckpt_dir):
            if name.startswith("step_") and name[5:].isdigit():
                out[int(name[5:])] = os.path.join(self.ckpt_dir, name)
        return out

    def _complete_steps(self) -> Dict[int, str]:
        """Only checkpoints whose commit manifest checks out (shallow) —
        torn dirs from a mid-save kill are invisible to discovery."""
        return {s: p for s, p in self._step_dirs().items()
                if is_complete_checkpoint(p)}

    def latest_step(self) -> Optional[int]:
        steps = self._complete_steps()
        return max(steps) if steps else None

    # -- save/restore -------------------------------------------------------
    def _state(self, model, optimizer=None):
        """Snapshot in TOPOLOGY-INDEPENDENT (canonical) form: pipeline-
        stacked params explode to per-layer entries and optimizer
        accumulators key by structured param path — so a checkpoint saved
        under dp x mp x pp restores under sharding-only (or any other
        hybrid config) and vice versa (the reference's auto-parallel
        checkpoint converter capability)."""
        from ...distributed.checkpoint.converter import canonical_state_dict

        return canonical_state_dict(model, optimizer)

    def maybe_save(self, step: int, model, optimizer=None, extra=None) -> bool:
        if (step + 1) % self.save_interval != 0:
            return False
        self.save(step, model, optimizer, extra)
        return True

    def flush(self):
        """Commit a pending async save (manifest + atomic rename). No-op
        when nothing is in flight."""
        pending, self._pending = self._pending, None
        if pending is not None:
            pending.wait_until_finished()
            # the commit just added a checkpoint; re-apply retention so an
            # async tail save doesn't leave max_to_keep+1 dirs behind
            self._gc()

    def _atexit_flush(self):
        try:
            self.flush()
        except Exception as e:  # interpreter teardown: diagnose, don't mask exit
            print(f"[elastic] final checkpoint flush failed: {e!r}",
                  file=sys.stderr)

    def save(self, step: int, model, optimizer=None, extra=None):
        """`extra` (user payload: rng state, epoch counters, ...) goes to a
        SIDECAR checkpoint next to the canonical one — the canonical tree
        stays exactly the live model/optimizer structure, so restore targets
        never have to guess shapes for keys that exist only on disk."""
        # a still-running async save must commit before the next write
        # starts: two writers interleaving in one directory tree is exactly
        # the torn state the manifest exists to rule out
        self.flush()
        path = os.path.join(self.ckpt_dir, f"step_{step}")
        self._pending = save_state_dict(
            self._state(model, optimizer), path, async_save=self.async_save
        )
        if extra:
            save_state_dict(dict(extra), self._extra_dir(step))
        self._gc()

    def _extra_dir(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, f"extra_{step}")

    def _gc(self):
        # retention counts COMMITTED checkpoints only, and even
        # max_to_keep=0 keeps the newest one: pruning must never leave the
        # job with no verified checkpoint to fall back to
        complete = sorted(self._complete_steps())
        keep = max(1, int(self.max_to_keep))
        for victim in complete[:-keep]:
            shutil.rmtree(os.path.join(self.ckpt_dir, f"step_{victim}"),
                          ignore_errors=True)
            shutil.rmtree(self._extra_dir(victim), ignore_errors=True)
        # sweep tmp leftovers from crashed writers — never the in-flight
        # save (matching by prefix: orbax stages the async body under
        # `<tmp>.orbax-checkpoint-tmp-<ts>` siblings of the target)
        active = os.path.basename(self._pending.tmp_path) if (
            self._pending is not None and hasattr(self._pending, "tmp_path")
        ) else None
        for name in os.listdir(self.ckpt_dir):
            if TMP_SUFFIX in name and not (active and name.startswith(active)):
                shutil.rmtree(os.path.join(self.ckpt_dir, name),
                              ignore_errors=True)

    # -- live resize --------------------------------------------------------
    def capture(self, model, optimizer=None) -> Dict:
        """Live canonical snapshot (arrays keep their CURRENT placements) —
        the source side of a live resize: capture on the old topology,
        rebuild model/optimizer on the new one, then ``live_resize``."""
        return self._state(model, optimizer)

    def live_resize(self, step: int, src_state: Dict, model,
                    optimizer=None) -> int:
        """Resume at ``step + 1`` on the CURRENT (resized) topology by
        resharding a captured live state via collectives — no disk
        round-trip. ``src_state`` is ``capture()``'s snapshot from before
        the fleet change; the rebuilt model/optimizer provide the target
        placements. Any failure (missing leaves — the survivors cannot
        host the state —, shape drift, a wedged collective) degrades to
        ``resume()`` from the newest verified checkpoint instead of
        crashing (graceful degradation; the fallback is telemetry-visible
        as ``reshard_fallback_total{why="disk_restore"}``)."""
        from ...distributed.checkpoint.converter import (
            apply_canonical, canonical_state_dict,
        )
        from ..reshard import record_fallback, reshard_state

        dst_state = canonical_state_dict(model, optimizer)
        try:
            moved = reshard_state(src_state, dst_state, what="live")
            apply_canonical(model, moved, optimizer)
        except (KeyError, ValueError, TimeoutError, RuntimeError) as e:
            print(f"[elastic] live resize at step {step} failed ({e!r}); "
                  "falling back to checkpoint restore", file=sys.stderr)
            record_fallback("disk_restore", step=step, error=repr(e))
            nxt = self.resume(model, optimizer)
            _obs.event("elastic_resize", step=step, outcome="disk_restore",
                       next_step=nxt)
            return nxt
        _obs.event("elastic_resize", step=step, outcome="live",
                   next_step=step + 1, leaves=len(dst_state))
        return step + 1

    def resume(self, model, optimizer=None, extra_out=None) -> int:
        """Restore the newest VERIFIED snapshot into the LIVE layout
        (re-stacking for the model's pipelines, re-placing onto current
        shardings); returns the next step index to run (0 when no usable
        checkpoint exists). Torn directories (no commit manifest) are
        skipped with a diagnosis; a committed checkpoint failing checksum
        verification falls back to the previous complete step. If every
        committed checkpoint is damaged, raises instead of silently
        training from scratch. If the snapshot was saved with ``extra=...``,
        pass a dict as ``extra_out`` to receive that payload back."""
        from ...distributed.checkpoint import load_state_dict
        from ...distributed.checkpoint.converter import (
            apply_canonical, restore_canonical,
        )

        self.flush()
        all_steps = self._step_dirs()
        complete = self._complete_steps()
        torn = sorted(set(all_steps) - set(complete))
        if torn:
            print(f"[elastic] ignoring torn/incomplete checkpoint dir(s) "
                  f"{['step_%d' % s for s in torn]} under {self.ckpt_dir} "
                  "(no commit manifest — writer died mid-save)",
                  file=sys.stderr)
        failures = []
        for step in sorted(complete, reverse=True):
            path = complete[step]
            if self.verify_on_resume:
                ok, why = verify_checkpoint(path, deep=True)
                if not ok:
                    print(f"[elastic] skipping step_{step}: {why}; falling "
                          "back to previous complete checkpoint",
                          file=sys.stderr)
                    failures.append((step, why))
                    _obs.inc("elastic_resume_fallback_total")
                    continue
            try:
                canonical = restore_canonical(path, model, optimizer)
                apply_canonical(model, canonical, optimizer)
            except Exception as e:
                print(f"[elastic] restore of step_{step} failed ({e!r}); "
                      "falling back to previous complete checkpoint",
                      file=sys.stderr)
                failures.append((step, repr(e)))
                _obs.inc("elastic_resume_fallback_total")
                continue
            if extra_out is not None and os.path.isdir(self._extra_dir(step)):
                extra_out.update(load_state_dict(self._extra_dir(step)))
            _obs.inc("elastic_resume_total")
            _obs.event("elastic_resume", step=step, next_step=step + 1,
                       torn=torn, fallbacks=len(failures), path=path)
            return step + 1
        if failures:
            raise RuntimeError(
                "every committed checkpoint under "
                f"{self.ckpt_dir} failed verification/restore: {failures}; "
                "refusing to silently train from scratch")
        return 0


# ---------------------------------------------------------------------------
# MPMD per-stage checkpoint shards + stage-local live resize
# ---------------------------------------------------------------------------
# An MPMD pipeline is S independent programs; its fault/elastic unit is ONE
# stage, not the world. Each stage checkpoints its own shard directory and
# resizes alone — the whole-fleet ElasticManager machinery above stays the
# SPMD path's driver.

def stage_shard_dir(base_dir: str, stage_id: int, step: int) -> str:
    return os.path.join(os.path.abspath(base_dir),
                        f"stage_{int(stage_id)}", f"step_{int(step)}")


def save_stage_shard(base_dir: str, stage_id: int, step: int,
                     state: Dict) -> str:
    """One stage's flat state (params/opt leaves by name) into its own
    commit-manifested shard dir — same atomic body->manifest->rename
    discipline as the whole-model checkpoints, so a SIGKILL mid-save
    leaves a torn dir that restore discovery skips."""
    path = stage_shard_dir(base_dir, stage_id, step)
    save_state_dict(dict(state), path)
    return path


def latest_stage_step(base_dir: str, stage_id: int) -> Optional[int]:
    """Newest COMMITTED shard step for one stage, or None."""
    root = os.path.join(os.path.abspath(base_dir), f"stage_{int(stage_id)}")
    if not os.path.isdir(root):
        return None
    steps = [int(n[5:]) for n in os.listdir(root)
             if n.startswith("step_") and n[5:].isdigit()
             and is_complete_checkpoint(os.path.join(root, n))]
    return max(steps) if steps else None


def latest_common_step(base_dir: str, num_stages: int) -> Optional[int]:
    """Newest step for which EVERY stage has a committed shard — the
    consistent restore point after a stage worker dies (the surviving
    stages may have saved one step further than the victim)."""
    steps = None
    for s in range(int(num_stages)):
        root = os.path.join(os.path.abspath(base_dir), f"stage_{s}")
        if not os.path.isdir(root):
            return None
        have = {int(n[5:]) for n in os.listdir(root)
                if n.startswith("step_") and n[5:].isdigit()
                and is_complete_checkpoint(os.path.join(root, n))}
        steps = have if steps is None else (steps & have)
        if not steps:
            return None
    return max(steps)


def load_stage_shard(base_dir: str, stage_id: int, step: int) -> Dict:
    from ..checkpoint import load_state_dict

    return load_state_dict(stage_shard_dir(base_dir, stage_id, step))


def stage_live_resize(stage_id: int, state: Dict, target_shardings: Dict):
    """Reshard ONE stage's live state onto its new placements (a width
    change for that stage alone). Every leaf moves via the planned
    ``reshard_array`` path (deadline-guarded device_put, stall telemetry);
    nothing outside this stage's state is touched — the other stages'
    arrays, executables and compile-cache entries survive as-is."""
    import time as _time

    from ..reshard import record_plan_metrics, reshard_array

    t0 = _time.perf_counter()
    out, plans = {}, []
    for name, arr in state.items():
        dst = target_shardings.get(name)
        if dst is None:
            out[name] = arr
            continue
        moved, plan = reshard_array(arr, dst, key=name)
        out[name] = moved
        plans.append(plan)
    record_plan_metrics(plans, what="mpmd_stage",
                        seconds=_time.perf_counter() - t0)
    _obs.event("elastic_stage_resize", stage=int(stage_id),
               leaves=len(plans))
    return out


# ---------------------------------------------------------------------------
# store-signaled fleet resize (the scale-event channel)
# ---------------------------------------------------------------------------
_RESIZE_KEY = "paddle_tpu/elastic/resize"


def request_resize(store, world_size: int) -> None:
    """Publish a fleet-resize request on the coordination store (bounded
    py_store op — deadlines/backoff per docs/FAULT_TOLERANCE.md). Workers
    polling ``poll_resize`` pick it up at their next step fence."""
    store.set(_RESIZE_KEY, str(int(world_size)))


def poll_resize(store) -> Optional[int]:
    """Non-blocking check for a pending resize request: the requested new
    world size, or None. The key stays set until ``clear_resize`` so late
    pollers (or a worker relaunched mid-resize) still observe it."""
    try:
        if not store.check(_RESIZE_KEY):
            return None
        v = store.get(_RESIZE_KEY)
        return int(v.decode() if isinstance(v, bytes) else v)
    except (TimeoutError, ValueError):
        return None


def clear_resize(store) -> None:
    """Acknowledge a completed resize (coordinator-side)."""
    try:
        store.delete_key(_RESIZE_KEY)
    except TimeoutError:
        pass


# stage-scoped variant: resize ONE pipeline stage's width, every other
# stage keeps running its compiled programs untouched
_STAGE_RESIZE_KEY = "paddle_tpu/elastic/stage_resize"


def request_stage_resize(store, stage_id: int, dp: int) -> None:
    """Publish a stage-local width change (``stage_id`` -> new dp). The
    MPMD driver picks it up at the next step fence and resizes only that
    stage (see distributed/mpmd.py)."""
    store.set(_STAGE_RESIZE_KEY, f"{int(stage_id)}:{int(dp)}")


def poll_stage_resize(store) -> Optional[Tuple[int, int]]:
    """Pending (stage_id, new_dp) stage resize, or None."""
    try:
        if not store.check(_STAGE_RESIZE_KEY):
            return None
        v = store.get(_STAGE_RESIZE_KEY)
        s, dp = (v.decode() if isinstance(v, bytes) else str(v)).split(":")
        return int(s), int(dp)
    except (TimeoutError, ValueError):
        return None


def clear_stage_resize(store) -> None:
    try:
        store.delete_key(_STAGE_RESIZE_KEY)
    except TimeoutError:
        pass
