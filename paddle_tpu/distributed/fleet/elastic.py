"""Elastic / fault-tolerant training (fleet.elastic parity, TPU-shaped).

Reference capability (SURVEY.md §5 "Failure detection/elastic"): etcd-backed
`ElasticManager` — node registry, heartbeat watch, scale events trigger
re-rendezvous and relaunch; training resumes from the last checkpoint.

TPU-native design (SURVEY.md §7 "Hard parts — Elastic"): TPU slices fail as
a UNIT, so elasticity is not rank replacement but **fast checkpoint-resume**:
a `CheckpointManager`-style loop (orbax-backed, async, sharded) snapshots
every N steps with bounded retention; on restart — same or different
topology — `latest_step()` + `restore()` re-shards onto the live mesh and
training continues. The etcd membership machinery has no analogue to port:
membership is the job scheduler's concern (GKE/Borg restart the slice).
"""
from __future__ import annotations

import os
from typing import Optional

from ..checkpoint import save_state_dict


class ElasticManager:
    """Checkpoint-resume driver. API kept close to a paddle training loop:

        elastic = ElasticManager(ckpt_dir, save_interval=100)
        start = elastic.resume(model, optimizer)          # 0 if fresh
        for step in range(start, total):
            loss = train_step(...)
            elastic.maybe_save(step, model, optimizer)
    """

    def __init__(self, ckpt_dir: str, save_interval: int = 100, max_to_keep: int = 3,
                 async_save: bool = False):
        self.ckpt_dir = os.path.abspath(ckpt_dir)
        self.save_interval = max(1, int(save_interval))
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self._pending = None

    # -- discovery ----------------------------------------------------------
    def _step_dirs(self):
        out = {}
        for name in os.listdir(self.ckpt_dir):
            if name.startswith("step_") and name[5:].isdigit():
                out[int(name[5:])] = os.path.join(self.ckpt_dir, name)
        return out

    def latest_step(self) -> Optional[int]:
        steps = self._step_dirs()
        return max(steps) if steps else None

    # -- save/restore -------------------------------------------------------
    def _state(self, model, optimizer=None):
        """Snapshot in TOPOLOGY-INDEPENDENT (canonical) form: pipeline-
        stacked params explode to per-layer entries and optimizer
        accumulators key by structured param path — so a checkpoint saved
        under dp x mp x pp restores under sharding-only (or any other
        hybrid config) and vice versa (the reference's auto-parallel
        checkpoint converter capability)."""
        from ...distributed.checkpoint.converter import canonical_state_dict

        return canonical_state_dict(model, optimizer)

    def maybe_save(self, step: int, model, optimizer=None, extra=None) -> bool:
        if (step + 1) % self.save_interval != 0:
            return False
        self.save(step, model, optimizer, extra)
        return True

    def save(self, step: int, model, optimizer=None, extra=None):
        """`extra` (user payload: rng state, epoch counters, ...) goes to a
        SIDECAR checkpoint next to the canonical one — the canonical tree
        stays exactly the live model/optimizer structure, so restore targets
        never have to guess shapes for keys that exist only on disk."""
        path = os.path.join(self.ckpt_dir, f"step_{step}")
        if self._pending is not None:
            try:
                self._pending.wait_until_finished()
            except Exception:
                pass
        self._pending = save_state_dict(
            self._state(model, optimizer), path, async_save=self.async_save
        )
        if extra:
            save_state_dict(dict(extra), self._extra_dir(step))
        self._gc()

    def _extra_dir(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, f"extra_{step}")

    def _gc(self):
        steps = sorted(self._step_dirs())
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            import shutil

            shutil.rmtree(os.path.join(self.ckpt_dir, f"step_{victim}"), ignore_errors=True)
            shutil.rmtree(self._extra_dir(victim), ignore_errors=True)

    def resume(self, model, optimizer=None, extra_out=None) -> int:
        """Restore latest snapshot into the LIVE layout (re-stacking for the
        model's pipelines, re-placing onto current shardings); returns the
        next step index to run (0 when no checkpoint exists). If the
        snapshot was saved with ``extra=...``, pass a dict as ``extra_out``
        to receive that payload back."""
        from ...distributed.checkpoint import load_state_dict
        from ...distributed.checkpoint.converter import (
            apply_canonical, restore_canonical,
        )

        step = self.latest_step()
        if step is None:
            return 0
        path = os.path.join(self.ckpt_dir, f"step_{step}")
        canonical = restore_canonical(path, model, optimizer)
        apply_canonical(model, canonical, optimizer)
        if extra_out is not None and os.path.isdir(self._extra_dir(step)):
            extra_out.update(load_state_dict(self._extra_dir(step)))
        return step + 1
