"""Fleet: the distributed training entry point (paddle.distributed.fleet parity).

Reference capability (SURVEY.md §2.3, §3.3): `fleet.init` builds the hybrid
topology + per-axis NCCL groups; `fleet.distributed_model` wraps the model per
strategy (DataParallel / TensorParallel / PipelineParallel / GroupSharded);
`fleet.distributed_optimizer` wraps the optimizer (HybridParallelOptimizer).

TPU-native design: `init` constructs the global named mesh; `distributed_model`
*places* parameters — device_put with the NamedSharding derived from each
parameter's `dist_spec` (tensor-parallel annotations from mpu layers) extended
by the FSDP/`sharding` axis per ZeRO stage; `distributed_optimizer` makes the
optimizer states follow (ZeRO-1/2 = opt-state sharded even where params are
replicated). The compiled train step (`DistTrainStep`) then jits the whole
fwd+bwd+update over the mesh: GSPMD turns the placement differences into the
reduce-scatter/all-gather patterns that the reference implements by hand in
GroupShardedStage{1,2,3} (§2.3 "Sharding (ZeRO-1/2/3)").
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.core import Tensor, no_grad
from ...framework.op import raw
from ...jit import TrainStep
from ...nn.layer import Layer
from .. import mesh as _mesh
from ..env import get_rank, get_world_size, init_parallel_env
from .strategy import DistributedStrategy
from .topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from . import topology  # noqa: F401
from .layers import mpu  # noqa: F401
from .utils import (  # noqa: F401
    recompute, recompute_hybrid, recompute_sequential, sequence_parallel_utils,
)

_strategy: Optional[DistributedStrategy] = None
_initialized = False


class UserDefinedRoleMaker:
    """Accepted for script compatibility; roles are implicit on TPU."""

    def __init__(self, *a, **k):
        pass


class PaddleCloudRoleMaker(UserDefinedRoleMaker):
    pass


def init(role_maker=None, is_collective: bool = False, strategy: Optional[DistributedStrategy] = None):
    """fleet.init parity — build the hybrid mesh from strategy.hybrid_configs."""
    global _strategy, _initialized
    init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    ndev = get_world_size()
    import os as _os
    if getattr(_strategy, "auto_plan", False) or \
            _os.environ.get("PADDLE_TPU_AUTO_PLAN", "") == "1":
        from ..auto_parallel import planner as _planner
        _planner.apply_auto_plan(_strategy, ndev)
    hc = _strategy.hybrid_configs
    mp = int(hc.get("mp_degree", 1))
    pp = int(hc.get("pp_degree", 1))
    sh = int(hc.get("sharding_degree", 1))
    sep = int(hc.get("sep_degree", 1))
    dp = int(hc.get("dp_degree", 1))
    fixed = mp * pp * sh * sep
    if dp in (-1, 0) or dp * fixed != ndev:
        if ndev % fixed != 0:
            raise ValueError(
                f"hybrid degrees mp={mp} pp={pp} sharding={sh} sep={sep} do not "
                f"divide device count {ndev}"
            )
        dp = ndev // fixed
        hc["dp_degree"] = dp
    topo = CommunicateTopology(
        hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
        dims=(dp, pp, sh, sep, mp),
    )
    hcg = HybridCommunicateGroup(topo)
    set_hybrid_communicate_group(hcg)
    _initialized = True
    return None


def is_initialized() -> bool:
    return _initialized


def fleet_strategy() -> Optional[DistributedStrategy]:
    return _strategy


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()


def is_first_worker() -> bool:
    return worker_index() == 0


def init_server(*model_paths, **kwargs):
    """PS-mode parity: on TPU there are no server processes — tables are
    mesh-sharded (see paddle_tpu.distributed.ps). Accepted as a no-op so
    PS-mode scripts run under the collective runtime."""


def run_server():
    """PS-mode parity no-op (no server loop to run; see distributed.ps)."""


def init_worker(scopes=None):
    """PS-mode parity: workers need no table-RPC setup under SPMD."""


def stop_worker():
    """PS-mode parity no-op."""


def is_server() -> bool:
    return False


def is_worker() -> bool:
    return True


def save_inference_model(executor=None, dirname=None, feeded_var_names=None,
                         target_vars=None, main_program=None,
                         export_for_deployment=True, mode=0,
                         path_prefix=None, feed_vars=None, fetch_vars=None,
                         model=None, input_spec=None, **kwargs):
    """fleet.save_inference_model parity: delegates to
    ``static.save_inference_model`` (StableHLO artifact + Predictor-loadable
    layout). Accepts both the legacy (dirname/feeded_var_names/target_vars)
    and modern (path_prefix/feed_vars/fetch_vars) reference argument names;
    the exported program comes from ``model`` (a Layer) or a Layer passed
    as fetch_vars/target_vars — the StableHLO exporter needs the callable,
    not captured variables."""
    from ... import static as _static

    prefix = path_prefix or dirname
    if prefix is None:
        raise ValueError("save_inference_model requires a path")
    feeds = feed_vars if feed_vars is not None else feeded_var_names
    fetches = fetch_vars if fetch_vars is not None else target_vars
    return _static.save_inference_model(
        prefix, feeds, fetches, executor, model=model,
        input_spec=input_spec, **kwargs)


def save_persistables(executor=None, dirname=None, main_program=None, mode=0,
                      model=None):
    """PS-mode checkpoint parity: persist every parameter (the whole model
    IS the 'table' under SPMD). Rides the sharding-aware orbax saver
    (distributed.checkpoint.save_state_dict), so mesh-sharded tables write
    shard-by-shard per host instead of materializing on one process.

    Sources, in priority order: `model` (a Layer) -> the static Program's
    static.nn parameters. Raises when there is nothing to save — a silent
    empty checkpoint is worse than an error."""
    import os as _os

    if dirname is None:
        raise ValueError("save_persistables requires dirname")
    from ...static import default_main_program
    from ...static.nn import static_parameters

    if model is not None:
        named = list(model.named_parameters())
    else:
        prog = main_program or default_main_program()
        named = [(f"p{i}", p) for i, p in enumerate(static_parameters(prog))]
    if not named:
        raise ValueError(
            "save_persistables found no parameters: pass model=<Layer> for "
            "dygraph scripts, or build the program with static.nn layers"
        )
    state = {n: p._value for n, p in named}
    from ..checkpoint import save_state_dict

    save_state_dict(state, _os.path.join(dirname, "persistables"))
    return list(state)


def barrier_worker():
    from ..collective import barrier

    barrier()


# ------------------------------------------------------------ param placement
def _extend_with_axis(spec: P, shape, axis_name: str, axis_size: int) -> P:
    """Add `axis_name` sharding on the first divisible, unsharded dim."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    flat = set()
    for e in entries:
        if isinstance(e, (tuple, list)):
            flat.update(e)
        elif e is not None:
            flat.add(e)
    if axis_name in flat:
        return P(*entries)
    # prefer the largest dim for even memory savings
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % axis_size == 0 and shape[i] >= axis_size:
            e = entries[i]
            if e is None:
                entries[i] = axis_name
                return P(*entries)
    return P(*entries)


def param_spec(p, *, fsdp: bool = False) -> P:
    """The parameter's full placement spec: TP annotation (+ FSDP extension)."""
    spec = getattr(p, "dist_spec", None) or P()
    m = _mesh.get_global_mesh()
    if m is None:
        return spec
    if fsdp and "sharding" in m.shape and m.shape["sharding"] > 1:
        spec = _extend_with_axis(spec, tuple(raw(p).shape), "sharding", m.shape["sharding"])
    return spec


def shard_model_parameters(model: Layer, *, fsdp: bool = False):
    """device_put every param/buffer to its mesh placement (TP + optional FSDP)."""
    m = _mesh.get_global_mesh()
    if m is None or m.size == 1:
        return model
    for _, p in model.named_parameters():
        spec = param_spec(p, fsdp=fsdp)
        p.dist_spec = spec
        p._rebind(_mesh.global_device_put(raw(p), spec, m))
    for _, b in model.named_buffers():
        b._rebind(_mesh.global_device_put(raw(b), P(), m))
    return model


def data_spec_for(shape) -> P:
    """Batch placement: dim 0 over the (dp, sharding) data axes when divisible."""
    m = _mesh.get_global_mesh()
    if m is None or not shape:
        return P()
    axes = tuple(a for a in ("dp", "sharding") if a in m.shape and m.shape[a] > 1)
    if not axes:
        return P()
    size = int(np.prod([m.shape[a] for a in axes]))
    if shape[0] % size != 0:
        return P()
    return P(axes if len(axes) > 1 else axes[0])


def distributed_model(model: Layer) -> Layer:
    """fleet.distributed_model parity: place params per strategy; wrap PP."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError("call fleet.init() before distributed_model")
    # ZeRO-3 ≡ params sharded over the sharding axis; else params replicated
    # over (dp, sharding) and only opt states sharded (stage 1/2, see
    # distributed_optimizer).
    stage3 = _strategy is not None and _strategy.sharding_configs.get("stage", 1) == 3
    shard_model_parameters(model, fsdp=stage3)
    from .meta_parallel.pipeline_parallel import PipelineLayer, PipelineParallel

    if hcg.get_pipe_parallel_world_size() > 1 and isinstance(model, PipelineLayer):
        # a PipelineLayer gets the train_batch driver; models embedding
        # SpmdPipeline internally need no wrapper
        return PipelineParallel(model, hcg, _strategy)
    return model


class HybridParallelOptimizer:
    """fleet.distributed_optimizer product: optimizer whose states live sharded.

    ZeRO stage 1/2 parity: moment/velocity accumulators are placed with the
    param's spec *extended by the sharding axis* — each sharding-group member
    owns a slice of optimizer state even when params are replicated. GSPMD
    compiles the update into reduce-scatter(grad) → local update → all-gather
    (param), the stage-2 comm pattern, automatically.
    """

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy or _strategy
        self._shard_states = (
            self._hcg is not None and self._hcg.get_sharding_parallel_world_size() > 1
        )
        # error-feedback residuals for quantized gradient exchange; set by
        # DistTrainStep when the explicit grad_comm path is active. They
        # ride the functional-state pytree (trailing entry) so the compiled
        # step threads them, but are NOT serialized: a restore restarts
        # quantization with a zero residual.
        self._grad_comm_residuals = None

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def _state_sharding(self, p, st: dict) -> dict:
        """Place every state leaf explicitly (committed arrays): param-shaped
        leaves follow the param's placement — extended by the `sharding` axis
        under ZeRO — scalars are replicated. Committed states keep the jit'ed
        step's input/output placements identical (donation-safe, no drift)."""
        m = _mesh.get_global_mesh()
        if m is None or m.size == 1:
            return st
        pshape = tuple(raw(p).shape)
        spec = param_spec(p)
        if self._shard_states:
            spec = _extend_with_axis(spec, pshape, "sharding", m.shape.get("sharding", 1))
        out = {}
        for k, v in st.items():
            if hasattr(v, "shape") and tuple(v.shape) == pshape:
                out[k] = _mesh.global_device_put(v, spec, m)
            elif hasattr(v, "shape"):
                out[k] = _mesh.global_device_put(v, P(), m)
            else:
                out[k] = v
        return out

    def functional_states(self):
        opt = self._inner_opt
        for i, p in enumerate(opt._parameter_list):
            if opt._accumulators[i] is None:
                opt._accumulators[i] = self._state_sharding(p, opt._init_state(p))
        out = list(opt._accumulators)
        if self._grad_comm_residuals is not None:
            from .. import grad_comm as _grad_comm

            out.append({_grad_comm.RESIDUAL_KEY: dict(self._grad_comm_residuals)})
        return out

    def _strip_residuals(self, states):
        from .. import grad_comm as _grad_comm

        states = list(states)
        if (states and isinstance(states[-1], dict)
                and _grad_comm.RESIDUAL_KEY in states[-1]):
            tail = states.pop()
            if self._grad_comm_residuals is not None:
                self._grad_comm_residuals = dict(tail[_grad_comm.RESIDUAL_KEY])
        return states

    def load_functional_states(self, states):
        self._inner_opt.load_functional_states(self._strip_residuals(states))

    def functional_step(self, param_vals, grad_vals, states, lr):
        return self._inner_opt.functional_step(
            param_vals, grad_vals, self._strip_residuals(states), lr)

    def functional_update(self, param_vals, grad_vals, states, lr):
        """Clip-free per-param update on whatever layout the caller hands in
        (the explicit grad_comm path calls this with SHARD-shaped params,
        gradients and states after its own shard-local clip)."""
        return self._inner_opt.functional_update(param_vals, grad_vals, states, lr)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, s):
        return self._inner_opt.set_state_dict(s)


class GradientMergeOptimizer:
    """strategy.gradient_merge meta-optimizer (reference:
    ``fleet/meta_optimizers/gradient_merge_optimizer.py`` — accumulate
    gradients for ``k_steps`` micro-steps, apply ONE optimizer update with
    the merged gradient, repeat).

    TPU-native design: the accumulator is part of the optimizer state, so
    the whole k-step cycle lives inside the one compiled train step — the
    boundary update is a ``lax.cond`` over the step counter, and the inner
    optimizer's own clip + weight-decay + rule run unchanged on the merged
    gradient (exactly the reference's boundary semantics; accumulation is
    fp32 regardless of the compute dtype). State leaves are param-shaped,
    so ZeRO placement via HybridParallelOptimizer applies to the
    accumulator too.
    """

    def __init__(self, inner, k_steps=1, avg=True):
        self._inner = inner
        self._k = max(int(k_steps), 1)
        self._avg = bool(avg)
        self._parameter_list = inner._parameter_list
        self._accumulators = [None] * len(self._parameter_list)
        self._eager_acc = None
        self._eager_ctr = 0

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._inner, name)

    @property
    def _use_master_weights(self):
        return self._inner._use_master_weights

    @_use_master_weights.setter
    def _use_master_weights(self, v):
        self._inner._use_master_weights = v

    @property
    def _grad_clip(self):
        return self._inner._grad_clip

    @property
    def _learning_rate(self):
        return self._inner._learning_rate

    def get_lr(self):
        return self._inner.get_lr()

    def set_lr(self, v):
        return self._inner.set_lr(v)

    def _init_state(self, p):
        st = {"gm_ctr": jnp.zeros((), jnp.int32),
              "gm_saw": jnp.zeros((), jnp.int32),
              "gm_acc": jnp.zeros(tuple(raw(p).shape), jnp.float32)}
        for k, v in self._inner._init_state(p).items():
            st[f"inner_{k}"] = v
        return st

    def functional_states(self):
        for i, p in enumerate(self._parameter_list):
            if self._accumulators[i] is None:
                self._accumulators[i] = self._init_state(p)
        return list(self._accumulators)

    def load_functional_states(self, states):
        self._accumulators = list(states)

    def functional_step(self, param_vals, grad_vals, states, lr):
        live = [g is not None and p.trainable
                for p, g in zip(self._parameter_list, grad_vals)]
        # Every trainable param participates in the boundary: one whose
        # grad is None right now may still hold accumulated gradient from
        # earlier micro-steps of the cycle — that must be applied AT the
        # boundary, not leak into the next cycle's average.
        part = [p.trainable for p in self._parameter_list]
        accs = [st["gm_acc"] + g.astype(jnp.float32) if ok
                else (st["gm_acc"] if p_ else None)
                for ok, p_, g, st in zip(live, part, grad_vals, states)]
        # gm_saw: traced received-a-grad-this-cycle flag. Inferring it
        # from acc != 0 would mis-skip a param whose real grads were
        # exactly zero (it must still get weight-decay/moment updates).
        saws = [jnp.maximum(st["gm_saw"], 1) if ok
                else (st["gm_saw"] if p_ else None)
                for ok, p_, st in zip(live, part, states)]
        inner_states = [
            {k[len("inner_"):]: v for k, v in st.items()
             if k.startswith("inner_")} if p_ else st
            for p_, st in zip(part, states)]
        try:
            first = live.index(True)
        except ValueError:
            return list(param_vals), list(states)
        ctr = states[first]["gm_ctr"] + 1
        is_boundary = ctr % self._k == 0

        def apply(_):
            scale = 1.0 / self._k if self._avg else 1.0
            merged = [
                (a * scale).astype(pv.dtype) if p_ else None
                for p_, a, pv in zip(part, accs, param_vals)]
            new_p, new_inner = self._inner.functional_step(
                param_vals, merged, inner_states, lr)
            # A participating-but-not-live param only truly updates if it
            # received a grad at some point this cycle: a never-grad
            # trainable param must not get weight-decay/moment updates
            # from a fabricated zero gradient.
            outs_p, outs_inner = [], []
            for ok, p_, sw, pv, np_, st_in, ni in zip(
                    live, part, saws, param_vals, new_p, inner_states,
                    new_inner):
                if p_ and not ok:
                    sel = sw != 0
                    np_ = jnp.where(sel, np_, pv)
                    ni = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(sel, new, old), ni, st_in)
                outs_p.append(np_)
                outs_inner.append(ni)
            zeroed = [jnp.zeros_like(a) if p_ else None
                      for p_, a in zip(part, accs)]
            return outs_p, zeroed, outs_inner

        def skip(_):
            return list(param_vals), accs, list(inner_states)

        new_p, new_accs, new_inner = jax.lax.cond(
            is_boundary, apply, skip, None)
        new_states = []
        for p_, st, a, sw, ni in zip(part, states, new_accs, saws, new_inner):
            if not p_:
                # non-trainable: carry state, but gm_ctr is CYCLE state —
                # advance it so liveness variation never desyncs a param
                # from the merge boundary — and the boundary clears the
                # accumulator/saw flag so a param frozen mid-cycle can't
                # leak its stale accumulated gradient into a later cycle
                # when unfrozen.
                out = dict(st)
                out["gm_ctr"] = ctr
                if "gm_acc" in out:
                    out["gm_acc"] = jnp.where(is_boundary, 0, out["gm_acc"])
                if "gm_saw" in out:
                    out["gm_saw"] = jnp.where(is_boundary, 0, out["gm_saw"])
                new_states.append(out)
                continue
            out = {"gm_ctr": ctr, "gm_acc": a,
                   "gm_saw": jnp.where(is_boundary, 0, sw)}
            out.update({f"inner_{k}": v for k, v in ni.items()})
            new_states.append(out)
        return new_p, new_states

    @no_grad()
    def step(self):
        """Eager-mode accumulation: every k-th call swaps the merged grads
        in and runs the inner optimizer's own step."""
        from ...framework.core import Tensor

        params = self._parameter_list
        if self._eager_acc is None:
            self._eager_acc = [None] * len(params)
        for i, p in enumerate(params):
            if p.trainable and p.grad is not None:
                g = raw(p.grad).astype(jnp.float32)
                self._eager_acc[i] = (g if self._eager_acc[i] is None
                                      else self._eager_acc[i] + g)
        self._eager_ctr += 1
        if self._eager_ctr % self._k:
            return
        scale = 1.0 / self._k if self._avg else 1.0
        saved = []
        for i, p in enumerate(params):
            saved.append(p.grad)
            if self._eager_acc[i] is not None:
                p.grad = Tensor(
                    (self._eager_acc[i] * scale).astype(raw(p).dtype))
        try:
            self._inner.step()
        finally:
            for p, g in zip(params, saved):
                p.grad = g
            self._eager_acc = [None] * len(params)

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def state_dict(self):
        """Serialize from the wrapper's OWN accumulators (the functional
        path stores the inner moments there as ``inner_*`` leaves plus the
        merge accumulator/counter — delegating to the inner optimizer would
        save nothing and silently reset moments on resume). Falls back to
        the inner state dict when only the eager path ran; in that case the
        mid-cycle eager accumulators and counter are serialized too, so a
        checkpoint taken between merge boundaries resumes without dropping
        up to k-1 micro-steps of accumulated gradient."""
        if not any(st is not None for st in self._accumulators):
            out = self._inner.state_dict()
            if self._eager_ctr % self._k:
                out["gm_eager_ctr"] = int(self._eager_ctr % self._k)
                for i, a in enumerate(self._eager_acc or []):
                    if a is not None:
                        name = self._parameter_list[i].name or f"param_{i}"
                        # COPY for the same donation reason as below
                        out[f"{name}.gm_eager_acc"] = Tensor(jnp.array(a))
            return out
        out = {}
        for i, st in enumerate(self._accumulators):
            if st is None:
                continue
            name = self._parameter_list[i].name or f"param_{i}"
            for k, v in st.items():
                # COPY: the live buffers are donated to the next compiled
                # step, which would delete the checkpoint out from under us
                out[f"{name}.{k}"] = (Tensor(jnp.array(v))
                                      if hasattr(v, "shape") else v)
        lr = self._inner._learning_rate
        if hasattr(lr, "state_dict"):
            out["LR_Scheduler"] = lr.state_dict()
        return out

    def set_state_dict(self, state):
        sched = state.get("LR_Scheduler") if hasattr(state, "get") else None
        lr = self._inner._learning_rate
        if sched and hasattr(lr, "set_state_dict"):
            lr.set_state_dict(sched)
        if hasattr(state, "get") and "gm_eager_ctr" in state:
            state = dict(state)
            self._eager_ctr = int(state.pop("gm_eager_ctr"))
            self._eager_acc = [None] * len(self._parameter_list)
            for i, p in enumerate(self._parameter_list):
                name = p.name or f"param_{i}"
                v = state.pop(f"{name}.gm_eager_acc", None)
                if v is not None:
                    self._eager_acc[i] = jnp.asarray(
                        raw(v) if isinstance(v, Tensor) else v, jnp.float32)
        else:
            # absence of eager keys means the checkpoint sits on a cycle
            # boundary: reset any stale in-memory mid-cycle state so a
            # rollback-restore doesn't merge a dropped micro-step's grads
            self._eager_ctr = 0
            self._eager_acc = None
        any_merged = False
        for i, p in enumerate(self._parameter_list):
            name = p.name or f"param_{i}"
            st = self._init_state(p)
            found = False
            for k in list(st):
                key = f"{name}.{k}"
                if key in state:
                    v = state[key]
                    st[k] = raw(v) if isinstance(v, Tensor) else v
                    found = True
            if found:
                if f"{name}.gm_saw" not in state:
                    # pre-gm_saw checkpoint: infer the received-a-grad flag
                    # from the accumulator, or a mid-cycle restore would
                    # silently drop this param's accumulated gradient at
                    # the next boundary
                    st["gm_saw"] = jnp.any(
                        st["gm_acc"] != 0).astype(jnp.int32)
                self._accumulators[i] = st
                any_merged = True
        if not any_merged:
            # checkpoint from a plain (non-merged) run: load inner moments
            return self._inner.set_state_dict(state)


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    strategy = strategy or _strategy
    for knob in ("dgc", "localsgd", "adaptive_localsgd"):
        if getattr(strategy, knob, False):
            import warnings

            warnings.warn(
                f"DistributedStrategy.{knob} is ignored on TPU: SPMD "
                "gradient all-reduce is exact and compiled into every step, "
                "so compressed (DGC) or periodically-averaged (LocalSGD) "
                "exchange has no XLA analogue (documented non-goal)",
                stacklevel=2)
    optimizer = _apply_meta_optimizers(optimizer, strategy)
    if getattr(strategy, "gradient_merge", False):
        cfg = dict(getattr(strategy, "gradient_merge_configs", {}) or {})
        optimizer = GradientMergeOptimizer(
            optimizer, k_steps=int(cfg.get("k_steps", 1)),
            avg=bool(cfg.get("avg", True)))
    return HybridParallelOptimizer(optimizer, get_hybrid_communicate_group(), strategy)


def _apply_meta_optimizers(optimizer, strategy):
    """Strategy-driven optimizer swaps, mirroring the reference's
    meta_optimizers (``fleet/meta_optimizers/lars_optimizer.py`` /
    ``lamb_optimizer.py``): with ``strategy.lars=True`` a Momentum
    optimizer becomes Lars (large-batch vision), with ``strategy.lamb=True``
    an Adam/AdamW becomes Lamb (large-batch LM). Other meta optimizers
    (amp / recompute / sharding / pipeline) are expressed as first-class
    mechanisms here rather than optimizer wrappers."""
    from ... import optimizer as opt_mod

    if getattr(strategy, "lars", False) and isinstance(optimizer, opt_mod.Momentum):
        cfg = dict(getattr(strategy, "lars_configs", {}) or {})
        new = opt_mod.Lars(
            learning_rate=optimizer._learning_rate,
            momentum=optimizer._momentum,
            lars_coeff=float(cfg.get("lars_coeff", 0.001)),
            lars_weight_decay=float(cfg.get("lars_weight_decay", 0.0005)),
            exclude_from_weight_decay=cfg.get("exclude_from_weight_decay", []),
            epsilon=float(cfg.get("epsilon", 0.0)),
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip,
        )
        # the reference meta optimizer forwards the wrapped optimizer's own
        # regularization alongside lars_weight_decay
        new._regularizer = optimizer._regularizer
        optimizer = new
    elif getattr(strategy, "lamb", False) and isinstance(
            optimizer, (opt_mod.Adam, opt_mod.AdamW)):
        cfg = dict(getattr(strategy, "lamb_configs", {}) or {})
        excl = list(cfg.get("exclude_from_weight_decay", []) or [])
        new = opt_mod.Lamb(
            learning_rate=optimizer._learning_rate,
            lamb_weight_decay=float(cfg.get("lamb_weight_decay", 0.01)),
            beta1=optimizer._beta1,
            beta2=optimizer._beta2,
            epsilon=optimizer._epsilon,
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip,
            exclude_from_weight_decay_fn=(
                (lambda pname: any(s in (pname or "") for s in excl))
                if excl else None
            ),
        )
        new._regularizer = optimizer._regularizer
        optimizer = new
    return optimizer


class DistTrainStep(TrainStep):
    """Sharded compiled train step: batch placed on the data axes, params and
    optimizer states already placed by distributed_model/optimizer — one jit
    over the mesh, XLA emits all collectives (SURVEY.md §7 step 6)."""

    def __init__(self, model, loss_fn, optimizer, donate=True):
        if not isinstance(optimizer, HybridParallelOptimizer):
            optimizer = HybridParallelOptimizer(optimizer)
        super().__init__(model, loss_fn, optimizer, donate=donate)
        self._grad_comm_cfg = None
        self._grad_comm_plan = None
        self._plan_grad_comm()

    def _plan_grad_comm(self):
        """Decide at construction whether the explicit bucketed/quantized
        data-parallel exchange replaces the GSPMD-derived one for this step
        (decided here, before the first functional_states() call, so the
        error-feedback residual entry is part of the state pytree from the
        start). Falls back to GSPMD whenever the mesh has model axes, any
        param is committed non-replicated (ZeRO-3: the pipeline/GSPMD path
        owns it), the optimizer chain merges gradients or keeps master
        weights, or the grad clip has no shard-local form."""
        from .. import grad_comm as _grad_comm

        m = _mesh.get_global_mesh()
        if m is None or m.size == 1:
            return
        cfg = _grad_comm.resolve_config(self._strategy_of())
        if not cfg.enable:
            return
        opt = self._opt
        if isinstance(opt._inner_opt, GradientMergeOptimizer):
            return
        if getattr(opt, "_use_master_weights", False):
            return
        if not _grad_comm.clip_supported(getattr(opt, "_grad_clip", None)):
            return
        for t in (*self._params, *self._buffers, *self._extra_params):
            sh = getattr(raw(t), "sharding", None)
            if isinstance(sh, NamedSharding) and tuple(sh.spec):
                return
        S = m.shape.get("sharding", 1)
        state_dims = []
        for p in self._params:
            k = None
            if opt._shard_states and S > 1:
                ext = _extend_with_axis(
                    param_spec(p), tuple(raw(p).shape), "sharding", S)
                k = _grad_comm.sharded_dim(ext, "sharding")
            state_dims.append(k)
        plan = _grad_comm.plan_dp_exchange(
            cfg, m,
            [tuple(raw(p).shape) for p in self._params],
            [jnp.dtype(raw(p).dtype).itemsize for p in self._params],
            [p.trainable for p in self._params],
            state_dims)
        if plan is None:
            return
        self._grad_comm_cfg = cfg
        self._grad_comm_plan = plan
        if cfg.quantized and cfg.error_feedback:
            self._opt._grad_comm_residuals = _grad_comm.init_residuals(
                cfg, plan, m)
        lays = tuple(plan.zero_layouts) + tuple(plan.tail_layouts)
        _grad_comm.record_build_stats(
            plan.n_buckets, plan.bytes_f32, plan.bytes_wire)
        # with in-backward tail issue (plan.overlap_tail) only the LAST-
        # finalizing bucket — the earliest parameters' — can't hide behind
        # remaining backward compute; the post-backward path has the same
        # shape (bucket 0 is still the last to finish), so one formula
        _grad_comm.record_overlap_ratio(lays[0].total * 4, plan.bytes_f32)

    def _strategy_of(self):
        return self._opt._strategy

    def _build_step(self):
        plan = self._grad_comm_plan
        if plan is None:
            return super()._build_step()
        from .. import grad_comm as _grad_comm

        m = _mesh.get_global_mesh()
        cfg = self._grad_comm_cfg
        changed = []
        loss_of = self._make_loss_of(changed)
        states = self._opt.functional_states()
        if (states and isinstance(states[-1], dict)
                and _grad_comm.RESIDUAL_KEY in states[-1]):
            states = states[:-1]

        def _spec(v):
            sh = getattr(v, "sharding", None)
            return sh.spec if isinstance(sh, NamedSharding) else P()

        state_specs = jax.tree_util.tree_map(_spec, list(states))
        return _grad_comm.build_explicit_dp_step(
            cfg, plan, m,
            loss_of=loss_of, opt=self._opt,
            trainable=[p.trainable for p in self._params],
            state_specs_tree=state_specs,
            batch_spec_fn=data_spec_for,
            buffer_changed_cell=changed,
            use_residuals=self._opt._grad_comm_residuals is not None)

    def _aot_key_parts(self):
        """Strategy + topology knobs for the persistent AOT compile cache:
        anything that reshapes the SPMD program (mesh split, schedule,
        bucketed exchange) must change the fingerprint even before the
        lowered-module hash diverges."""
        parts = super()._aot_key_parts()
        strat = self._strategy_of()
        if strat is not None:
            parts["hybrid"] = dict(strat.hybrid_configs)
            parts["pipeline"] = dict(strat.pipeline_configs)
            parts["grad_comm"] = dict(strat.grad_comm_configs)
            parts["sharding"] = dict(strat.sharding_configs)
            parts["bucket_mb"] = strat.fuse_grad_size_in_MB
        plan = self._grad_comm_plan
        parts["grad_comm_buckets"] = None if plan is None else plan.n_buckets
        return parts

    def _aot_mesh(self):
        return _mesh.get_global_mesh()

    def _dispatch(self, key, build, batch_vals):
        out = super()._dispatch(key, build, batch_vals)
        plan = self._grad_comm_plan
        if plan is not None:
            from .. import grad_comm as _grad_comm

            steps = key[2] if key and key[0] == "multi" else 1
            _grad_comm.record_step_bytes(plan.bytes_wire * steps)
        return out

    def _place_batch(self, batch_vals):
        m = _mesh.get_global_mesh()
        if m is None or m.size == 1:
            return batch_vals
        out = []
        for v in batch_vals:
            out.append(_mesh.global_device_put(
                v, data_spec_for(tuple(v.shape)), m))
        return out

    def _jit(self, step):
        """jit with pinned output shardings so updated params/opt-states land
        back exactly where they started. Without this, XLA propagates the
        sharded opt-state layout into the new params (placement drift: ZeRO-1
        silently becomes ZeRO-3 after the first step, and every step
        recompiles)."""
        m = _mesh.get_global_mesh()
        if m is None or m.size == 1:
            return super()._jit(step)
        repl = NamedSharding(m, P())

        def _of(v):
            sh = getattr(v, "sharding", None)
            return sh if isinstance(sh, NamedSharding) else repl

        p_sh = [_of(raw(p)) for p in self._params]
        b_sh = [_of(raw(b)) for b in self._buffers + self._extra_params]
        st_sh = jax.tree_util.tree_map(_of, self._opt.functional_states())
        donate = (0, 2) if self._donate else ()
        return jax.jit(
            step,
            donate_argnums=donate,
            out_shardings=(repl, p_sh, b_sh, st_sh),
        )


# imported last: meta_parallel's sharding module needs HybridParallelOptimizer
from . import meta_parallel  # noqa: F401,E402

from .. import ps  # noqa: E402,F401  (paddle.distributed.ps equivalent)

__all__ = [
    "init_server",
    "run_server",
    "init_worker",
    "stop_worker",
    "is_server",
    "is_worker",
    "save_persistables",
    "init",
    "DistributedStrategy",
    "distributed_model",
    "distributed_optimizer",
    "DistTrainStep",
    "HybridParallelOptimizer",
    "CommunicateTopology",
    "HybridCommunicateGroup",
    "get_hybrid_communicate_group",
    "worker_index",
    "worker_num",
    "is_first_worker",
    "barrier_worker",
    "shard_model_parameters",
    "param_spec",
    "data_spec_for",
    "UserDefinedRoleMaker",
    "PaddleCloudRoleMaker",
]


class UtilBase:
    """fleet.util parity (reference: fleet/utils/fleet_util.py UtilBase):
    small cross-worker helpers over the collective runtime + a filesystem
    handle."""

    def __init__(self):
        from .utils.fs import LocalFS

        self._fs = LocalFS()

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from .. import collective as C
        from ...framework.core import Tensor

        t = input if isinstance(input, Tensor) else Tensor(np.asarray(input))
        op = {"sum": C.ReduceOp.SUM, "min": C.ReduceOp.MIN,
              "max": C.ReduceOp.MAX}[mode]
        return C.all_reduce(t, op=op)

    def barrier(self, comm_world="worker"):
        from .. import collective as C

        C.barrier()

    def all_gather(self, input, comm_world="worker"):
        from .. import collective as C
        from ...framework.core import Tensor
        import numpy as np

        t = input if isinstance(input, Tensor) else Tensor(np.asarray(input))
        return C.all_gather(None, t)

    def get_file_shard(self, files):
        """Split a file list evenly over workers (PS data sharding)."""
        from .. import get_rank, get_world_size

        n, r = get_world_size(), max(get_rank(), 0)
        per, extra = divmod(len(files), n)
        start = r * per + min(r, extra)
        return files[start: start + per + (1 if r < extra else 0)]

    def set_file_system(self, fs):
        self._fs = fs

    @property
    def fs(self):
        return self._fs


util = UtilBase()
