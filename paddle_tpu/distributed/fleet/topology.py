"""Hybrid-parallel topology (fleet.base.topology parity).

Reference capability (SURVEY.md §2.3 "Hybrid topology",
`python/paddle/distributed/fleet/base/topology.py`): `CommunicateTopology`
lays ranks out on a [dp, pp, sharding, sep, mp] grid; `HybridCommunicateGroup`
derives per-axis subgroups (NCCL communicators) and this rank's coordinate.

TPU-native design: the grid IS a `jax.sharding.Mesh` with named axes — the
subgroup-per-axis machinery collapses into axis names. Axis order puts `mp`
innermost so tensor-parallel collectives ride same-host/neighbor ICI links
and `dp` outermost (slowest links / DCN across slices) — the same locality
rule the reference encodes by ordering, now enforced by mesh construction.
`sharding` doubles as the FSDP/ZeRO axis (§2.3 "Sharding (ZeRO-1/2/3)").
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .. import mesh as _mesh
from ..env import Group

_HYBRID_ORDER = ["data", "pipe", "sharding", "sep", "model"]
_AXIS_ALIAS = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep", "model": "mp"}


class CommunicateTopology:
    def __init__(
        self,
        hybrid_group_names: Sequence[str] = ("data", "pipe", "sharding", "sep", "model"),
        dims: Sequence[int] = (1, 1, 1, 1, 1),
    ):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self.coordinate = itertools.product(*(range(d) for d in self._dims))
        self._world_size = int(np.prod(self._dims))
        ranks = np.arange(self._world_size).reshape(self._dims)
        self._rank_grid = ranks
        self._coord_of_rank = {
            int(ranks[c]): c for c in itertools.product(*(range(d) for d in self._dims))
        }

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return int(self._rank_grid[coord])

    def get_coord(self, rank: int):
        return self._coord_of_rank[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        take = np.take(self._rank_grid, index, axis=axis)
        return [int(r) for r in take.ravel()]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All subgroups along `axis_name`: ranks varying only on that axis."""
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._rank_grid, axis, -1).reshape(-1, self._dims[axis])
        return [[int(r) for r in row] for row in moved]


class HybridCommunicateGroup:
    """Rank-coordinate + per-axis group view over the global hybrid mesh."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.nranks = topology.world_size()
        ndev = len(jax.devices())
        if self.nranks != ndev:
            raise ValueError(
                f"hybrid topology spans {self.nranks} ranks but {ndev} devices "
                "are visible; degrees must multiply to the device count"
            )
        names = topology.get_hybrid_group_names()
        mesh_axes = tuple(_AXIS_ALIAS[n] for n in names)
        dims = tuple(topology.get_dim(n) for n in names)
        self.mesh = _mesh.build_hybrid_mesh(dims, mesh_axes)
        _mesh.set_global_mesh(self.mesh)

        self.global_rank = 0  # single-controller: coordinate of device 0
        self._coord = topology.get_coord(self.global_rank)

        self._dp_degree = topology.get_dim("data") if "data" in names else 1
        self._pp_degree = topology.get_dim("pipe") if "pipe" in names else 1
        self._sharding_degree = topology.get_dim("sharding") if "sharding" in names else 1
        self._sep_degree = topology.get_dim("sep") if "sep" in names else 1
        self._mp_degree = topology.get_dim("model") if "model" in names else 1

        self._groups: Dict[str, Group] = {}
        for n in names:
            axis = _AXIS_ALIAS[n]
            idx = {m: self._coord[i] for i, m in enumerate(names) if m != n}
            # ranks of this rank's subgroup along axis n
            sub = self._sub_ranks(n)
            self._groups[axis] = Group(sub, axis_names=(axis,), name=f"{axis}_group")

    def _sub_ranks(self, axis_name: str) -> List[int]:
        names = self._topo.get_hybrid_group_names()
        coord = dict(zip(names, self._coord))
        ranks = []
        for i in range(self._topo.get_dim(axis_name)):
            c = dict(coord)
            c[axis_name] = i
            ranks.append(self._topo.get_rank(**c))
        return ranks

    # --- topology info (fleet parity names) --------------------------------
    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._mp_degree > 1:
            return "model"
        return "data"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord[0]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self) -> Group:
        return self._groups["dp"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["dp"].ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord[-1]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self) -> Group:
        return self._groups["mp"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["mp"].ranks[0]

    # pipeline
    def get_stage_id(self):
        return self._coord[1]

    def get_pipe_parallel_rank(self):
        return self._coord[1]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pp"]

    def get_p2p_groups(self):
        return self._groups["pp"]

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord[2]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return self._groups["sharding"].ranks[0]

    # sep (Ulysses sequence parallel)
    def get_sep_parallel_rank(self):
        return self._coord[3]

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self) -> Group:
        return self._groups["sep"]

    # checks
    def get_check_parallel_group(self, *a, **k) -> Group:
        return self._groups["mp"]

    def get_rank_from_stage(self, stage_id, **kwargs):
        names = self._topo.get_hybrid_group_names()
        coord = dict(zip(names, self._coord))
        coord["pipe"] = stage_id
        coord.update(kwargs)
        return self._topo.get_rank(**coord)


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
