"""Tensor-parallel (Megatron-style) layers — fleet.layers.mpu parity.

Reference capability (SURVEY.md §2.3 "Tensor/model parallel",
`python/paddle/distributed/fleet/layers/mpu/mp_layers.py`): each rank holds a
weight shard and the forward/backward insert explicit NCCL collectives —
ColumnParallelLinear (identity fwd / allreduce bwd), RowParallelLinear
(allreduce fwd), VocabParallelEmbedding (mask + allreduce), and
ParallelCrossEntropy (`c_softmax_with_cross_entropy`); RNG decorrelation via
`mpu/random.py` RNGStatesTracker.

TPU-native design: the layers hold the *full logical* parameter annotated
with a PartitionSpec on the `mp` mesh axis (`weight.dist_spec`); GSPMD
partitions the matmul and inserts the identical allreduce/allgather pattern
at compile time. The explicit f/g conjugate-function machinery of Megatron
disappears — `sharding_constraint` on activations is the only hand annotation
(it is what makes XLA choose the Megatron comm pattern instead of
re-replicating). The classes remain so Paddle hybrid-parallel model code
ports verbatim, and so parameter shardings can be harvested by the sharded
train step (fleet.distributed_optimizer).
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....framework import rng as _rng
from ....framework.core import Tensor
from ....framework.op import defop, raw
from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer import Layer, ParamAttr
from ... import mesh as _mesh
from ..topology import get_hybrid_communicate_group


def _data_axes():
    """Mesh axes that shard the batch dim of activations ((dp, sharding))."""
    m = _mesh.get_global_mesh()
    if m is None:
        return None
    axes = tuple(a for a in ("dp", "sharding") if a in m.shape and m.shape[a] > 1)
    if not axes:
        return None
    return axes if len(axes) > 1 else axes[0]


def _has_mp() -> bool:
    m = _mesh.get_global_mesh()
    return m is not None and "mp" in m.shape


@defop(name="mp_reshard")
def _reshard(x, spec: P):
    return _mesh.sharding_constraint(x, spec)


# ------------------------------------------------------- activation wire
# Quantized mp collectives (distributed/mp_comm.py): when the activation
# wire is on, the parallel layers route their contraction through the
# blocked recombination — per-shard partial sums cross the mesh at
# bf16/int8 with f32 accumulation — instead of GSPMD's implicit f32
# all-reduce. Resolved per trace so `PADDLE_TPU_MP_COMM` and the engine's
# `activation_wire_disabled()` scope both take effect without rebuilds.

def _mp_wire_cfg(world_size: int):
    if world_size <= 1 or not _has_mp():
        return None
    from ... import mp_comm as _mp_comm

    cfg = _mp_comm.resolve_config()
    return cfg if cfg.quantized else None


def _wire_out_dtype(*vals):
    return jnp.result_type(*[v.dtype for v in vals])


@defop(name="mp_wire_row_linear")
def _row_linear_wire(x, w, g: int, wire_dtype: str):
    from ... import mp_comm as _mp_comm

    out = _mp_comm.row_parallel_matmul(x, w, g, wire_dtype, _data_axes())
    return out.astype(_wire_out_dtype(x, w))


@defop(name="mp_wire_col_linear")
def _col_linear_wire(x, w, g: int, wire_dtype: str):
    from ... import mp_comm as _mp_comm

    out = _mp_comm.column_parallel_linear(x, w, g, wire_dtype, _data_axes())
    return out.astype(_wire_out_dtype(x, w))


@defop(name="mp_wire_vocab_embedding")
def _vocab_embed_wire(w, ids, g: int, wire_dtype: str):
    from ... import mp_comm as _mp_comm

    out = _mp_comm.vocab_parallel_embedding(w, ids, g, wire_dtype,
                                            _data_axes())
    return out.astype(w.dtype)


def mp_wire_linear(x, w, world_size: int):
    """Column-form linear for the tied LM head (``w [H, V]`` with the
    output/vocab dim mp-sharded): identical to ``F.linear(x, w)`` when the
    activation wire is off; with it on, the backward dx recombination —
    the layer's one mp collective — rides the quantized blocked wire."""
    cfg = _mp_wire_cfg(world_size)
    if cfg is None or int(w.shape[-1]) % world_size != 0:
        return F.linear(x, w)
    return _col_linear_wire(x, w, world_size, cfg.wire_dtype)


def mark_activation(x, *, last_mp: bool = False, seq_mp: bool = False, seq_dim: int = 1):
    """Constrain an activation's layout: batch on (dp, sharding), optionally
    hidden on mp (column-parallel output) or sequence on mp (Megatron-SP)."""
    m = _mesh.get_global_mesh()
    if m is None:
        return x
    nd = x.ndim
    spec = [None] * nd
    spec[0] = _data_axes()
    if last_mp and _has_mp():
        spec[nd - 1] = "mp"
    if seq_mp and _has_mp():
        spec[seq_dim] = "mp"
    return _reshard(x, P(*spec))


class ColumnParallelLinear(Layer):
    """y = x @ W[:, shard] — W sharded on the output dim over `mp`."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr=None,
        has_bias: bool = True,
        gather_output: bool = True,
        fuse_matmul_bias: bool = False,
        mp_group=None,
        name=None,
    ):
        super().__init__()
        hcg = get_hybrid_communicate_group()
        self.world_size = (
            mp_group.nranks if mp_group is not None
            else (hcg.get_model_parallel_world_size() if hcg else 1)
        )
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree {self.world_size}"
            )
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        self.weight.dist_spec = P(None, "mp")
        self.weight.is_distributed = True
        self.weight.split_axis = 1
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_spec = P("mp")
            self.bias.is_distributed = True
            self.bias.split_axis = 0
        else:
            self.bias = None

    def forward(self, x):
        cfg = _mp_wire_cfg(self.world_size)
        if cfg is not None:
            # fwd is collective-free (y stays mp-sharded); the wire rides
            # the backward dx recombination
            y = _col_linear_wire(x, self.weight, self.world_size,
                                 cfg.wire_dtype)
            if self.bias is not None:
                y = y + self.bias
            return mark_activation(y, last_mp=not self.gather_output)
        y = F.linear(x, self.weight, self.bias)
        return mark_activation(y, last_mp=not self.gather_output)


class RowParallelLinear(Layer):
    """y = x[shard] @ W[shard, :] + allreduce — W sharded on the input dim."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr=None,
        has_bias: bool = True,
        input_is_parallel: bool = False,
        fuse_matmul_bias: bool = False,
        mp_group=None,
        name=None,
    ):
        super().__init__()
        hcg = get_hybrid_communicate_group()
        self.world_size = (
            mp_group.nranks if mp_group is not None
            else (hcg.get_model_parallel_world_size() if hcg else 1)
        )
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree {self.world_size}"
            )
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True
        self.weight.split_axis = 0
        if has_bias:
            # bias applied after the (implicit) allreduce — replicated
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.dist_spec = P(None)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = mark_activation(x, last_mp=True)
        cfg = _mp_wire_cfg(self.world_size)
        if cfg is not None:
            # the fwd all-reduce is THE row-parallel collective: recombine
            # the per-shard partials through the quantized blocked wire
            y = _row_linear_wire(x, self.weight, self.world_size,
                                 cfg.wire_dtype)
            if self.bias is not None:
                y = y + self.bias
            return mark_activation(y)
        y = F.linear(x, self.weight, self.bias)
        # GSPMD: contraction over the mp-sharded dim → partial-sum → allreduce
        return mark_activation(y)


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over the vocab dim on `mp`.

    The reference masks out-of-shard ids and allreduces
    (`c_embedding` — SURVEY.md §2.3 "Collective ops"); GSPMD derives the same
    dynamic-slice + allreduce from the table's sharding.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        hcg = get_hybrid_communicate_group()
        self.world_size = (
            mp_group.nranks if mp_group is not None
            else (hcg.get_model_parallel_world_size() if hcg else 1)
        )
        if num_embeddings % max(self.world_size, 1) != 0:
            raise ValueError(
                f"vocab {num_embeddings} not divisible by mp degree {self.world_size}"
            )
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(std=0.02),
        )
        self.weight.dist_spec = P("mp", None)
        self.weight.is_distributed = True
        self.weight.split_axis = 0

    def forward(self, x):
        cfg = _mp_wire_cfg(self.world_size)
        if cfg is not None:
            # one-hot-matmul lowering of the sharded-table gather; the
            # mask+allreduce recombination rides the quantized wire
            y = _vocab_embed_wire(self.weight, x, self.world_size,
                                  cfg.wire_dtype)
            return mark_activation(y)
        y = F.embedding(x, self.weight)
        return mark_activation(y)


@defop(name="parallel_cross_entropy")
def _parallel_softmax_ce(logits, label, ignore_index):
    # Numerically-stable CE; when logits' vocab dim is mp-sharded GSPMD
    # computes the max/sum reductions with allreduces over mp — the same
    # pattern as the reference's fused c_softmax_with_cross_entropy.
    m = jnp.max(logits, axis=-1, keepdims=True)
    shifted = logits - jax.lax.stop_gradient(m)
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True))
    logprobs = shifted - lse
    label_ = jnp.where(label == ignore_index, 0, label)
    picked = jnp.take_along_axis(logprobs, label_[..., None], axis=-1)[..., 0]
    loss = -jnp.where(label == ignore_index, 0.0, picked)
    return loss


class ParallelCrossEntropy(Layer):
    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return _parallel_softmax_ce(input, raw(label), self.ignore_index)


# --------------------------------------------------------------- RNG tracker
class RNGStatesTracker:
    """mpu.random.RNGStatesTracker parity: named decorrelated RNG streams.

    Megatron needs per-rank local seeds so dropout masks on mp-sharded
    activations differ per shard while replicated tensors share masks. Under
    GSPMD, tensors are globally consistent and one counter-based key suffices
    for correctness; we still fold the stream name (and a per-name seed) into
    the key so `get_rng_state_tracker().rng_state("local_seed")` produces an
    independent stream, matching reference script behavior.
    """

    def __init__(self):
        self._seeds = {}

    def add(self, name: str, seed: int):
        if name in self._seeds:
            raise ValueError(f"seed name {name} already added")
        self._seeds[name] = _rng.Generator(seed)

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self._seeds.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            self._seeds.setdefault(n, _rng.Generator(0)).set_state(s)

    @contextlib.contextmanager
    def rng_state(self, name: str = "model_parallel_rng"):
        if name not in self._seeds:
            self.add(name, hash(name) % (2**31))
        gen = self._seeds[name]
        if _rng.in_trace_scope():
            # inside a compiled program: derive from the trace key + name
            with _rng.trace_key_scope(
                jax.random.fold_in(_rng.next_key(), hash(name) % (2**31))
            ):
                yield
        else:
            prev = _rng._default_generator
            _rng._default_generator = gen
            try:
                yield
            finally:
                _rng._default_generator = prev


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed: int = 0):
    global _tracker
    _tracker = RNGStatesTracker()
    _tracker.add("global_seed", seed)
    _tracker.add("local_seed", seed + 1024)
