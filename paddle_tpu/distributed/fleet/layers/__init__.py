from . import mpu  # noqa: F401
from .mpu import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
)
