"""DistributedStrategy (fleet.DistributedStrategy parity).

Reference: a protobuf (`paddle/fluid/framework/distributed_strategy.proto`)
wrapped by `fleet/base/distributed_strategy.py` holding every distributed
knob (SURVEY.md §5 "Config/flag system"). TPU-native design: one typed
Python object — no proto round-trip; only the knobs that are meaningful
under XLA/SPMD do anything, the rest are accepted for script compatibility
and recorded (so a Paddle training script's strategy blocks run unchanged).
"""
from __future__ import annotations

from typing import Any, Dict


class _SubConfig(dict):
    """Dict with attribute access, tolerant of unknown keys."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees — mirror of strategy.hybrid_configs
        self.hybrid_configs: _SubConfig = _SubConfig(
            dp_degree=1,
            mp_degree=1,
            pp_degree=1,
            sharding_degree=1,
            sep_degree=1,
            order=["dp", "pp", "sharding", "sep", "mp"],
        )
        # amp — maps to bf16-first autocast (GradScaler vestigial on TPU)
        self.amp = False
        self.amp_configs: _SubConfig = _SubConfig(
            init_loss_scaling=32768.0,
            use_dynamic_loss_scaling=True,
            use_pure_fp16=False,
            use_bf16=True,
            custom_white_list=[],
            custom_black_list=[],
        )
        # recompute — maps to jax.checkpoint policy on marked blocks
        self.recompute = False
        self.recompute_configs: _SubConfig = _SubConfig(checkpoints=[])
        # sharding (ZeRO) — maps to param/opt-state sharding specs
        self.sharding = False
        self.sharding_configs: _SubConfig = _SubConfig(
            sharding_degree=1, stage=1, offload=False
        )
        # pipeline. `schedule` picks the compiled micro-batch schedule
        # (gpipe | 1f1b | zero_bubble — see docs/PIPELINE.md) and
        # `virtual_pp_degree` the interleaving factor; both are resolved by
        # meta_parallel.pipeline_parallel.resolve_pp_schedule with a
        # PADDLE_TPU_PP_SCHEDULE env override. schedule_mode is the
        # reference's legacy spelling, accepted but subordinate.
        self.pipeline = False
        self.pipeline_configs: _SubConfig = _SubConfig(
            micro_batch_size=1, accumulate_steps=1, schedule_mode="1F1B",
            schedule="gpipe", virtual_pp_degree=1,
        )
        self.gradient_merge = False
        self.gradient_merge_configs: _SubConfig = _SubConfig(k_steps=1, avg=True)
        self.lamb = False
        self.lamb_configs: _SubConfig = _SubConfig(
            lamb_weight_decay=0.01, exclude_from_weight_decay=[]
        )
        self.lars = False
        self.lars_configs: _SubConfig = _SubConfig(
            lars_coeff=0.001, lars_weight_decay=0.0005,
            exclude_from_weight_decay=[], epsilon=0.0,
        )
        # dgc / localsgd: accepted for surface parity; distributed_optimizer
        # WARNS and ignores them — SPMD all-reduce is exact and every-step
        # (compiled into the program), so sparse-compressed (DGC) or
        # periodically-averaged (LocalSGD) gradient exchange has no XLA
        # analogue. Deliberate non-goal, not a silent accept.
        self.dgc = False
        self.dgc_configs: _SubConfig = _SubConfig(rampup_begin_step=0)
        self.localsgd = False
        self.localsgd_configs: _SubConfig = _SubConfig(k_steps=1, begin_step=1)
        self.adaptive_localsgd = False
        self.fuse_all_reduce_ops = True  # no-op: XLA fuses
        self.fuse_grad_size_in_MB = 32
        # explicit gradient-communication layer (distributed/grad_comm.py):
        # bucketed, overlap-friendly collectives with optional reduced-
        # precision wire (bf16/int8 + error feedback) and ZeRO weight-update
        # sharding. Off by default — the GSPMD-derived exchange remains the
        # baseline; PADDLE_TPU_GRAD_COMM overrides these knobs per run.
        # bucket_mb is deliberately ABSENT here: unset, the bucket size
        # defaults to fuse_grad_size_in_MB (the reference's fused-allreduce
        # buffer knob) so tuned ports keep their comm granularity.
        # `overlap` issues each tail bucket's collective inside the backward
        # chain (as its cotangents finalize) instead of after the full
        # backward; kill switch overlap=0 in PADDLE_TPU_GRAD_COMM.
        self.grad_comm = False
        self.grad_comm_configs: _SubConfig = _SubConfig(
            wire_dtype="f32", error_feedback=False,
            zero_update=True, pipeline_batch_shard=True, overlap=True,
        )
        # activation wire (distributed/mp_comm.py): quantized mp/sharding
        # activation collectives — blocked recombination of Row/Column/
        # Vocab-parallel partial sums at bf16/int8 with f32 accumulation,
        # quantized ZeRO parameter all-gathers (floored at bf16), and the
        # decode logit recombination with exact-argmax verify. Same env
        # grammar as grad_comm under PADDLE_TPU_MP_COMM; off by default —
        # the exact GSPMD collectives remain the baseline.
        self.mp_comm = False
        self.mp_comm_configs: _SubConfig = _SubConfig(
            wire_dtype="f32", error_feedback=False,
            zero_gather=True, logit_verify=True,
        )
        self.nccl_comm_num = 1
        self.find_unused_parameters = False
        self.without_graph_optimization = False
        self.tensor_parallel = False
        self.tensor_parallel_configs: _SubConfig = _SubConfig(
            tensor_parallel_degree=1, tensor_init_seed=-1
        )
        # auto-parallel: fleet.init() runs the cost-model planner
        # (distributed/auto_parallel/planner.py) and fills in any hybrid/
        # pipeline knob still at its default. Manual settings always win —
        # the planner only writes knobs the user left untouched. Also
        # reachable without code changes via PADDLE_TPU_AUTO_PLAN=1.
        self.auto_plan = False
        self.auto_plan_configs: _SubConfig = _SubConfig(model_config=None)

    @classmethod
    def auto(cls, model_config: Any = None) -> "DistributedStrategy":
        """A strategy whose layout is chosen by the auto-parallel planner.

        ``model_config`` (a ``planner.ModelConfig`` or plain dict of its
        fields) describes the workload for the cost model; None lets the
        planner fall back to the calibration proxy's shape. Any knob set
        manually on the returned strategy afterwards is pinned — the
        planner never overrides a non-default value.
        """
        s = cls()
        s.auto_plan = True
        s.auto_plan_configs.model_config = model_config
        return s

    def to_dict(self) -> Dict[str, Any]:
        return {
            k: (dict(v) if isinstance(v, _SubConfig) else v)
            for k, v in self.__dict__.items()
        }

    def __repr__(self):
        lines = ["DistributedStrategy("]
        for k, v in self.to_dict().items():
            lines.append(f"  {k}={v},")
        return "\n".join(lines) + ")"
