"""fleet.utils.mix_precision_utils parity.

Reference: ``python/paddle/distributed/fleet/utils/mix_precision_utils.py`` —
``MixPrecisionLayer`` keeps a float32 ``main_grad`` per low-precision
parameter (grads accumulate in fp32 regardless of the compute dtype) and
``MixPrecisionOptimizer`` steps from those master grads.

TPU-native design: the same contract falls out of the existing O2 machinery —
``amp.decorate`` casts parameters to the low-precision dtype and flips the
optimizer to fp32 master weights, and the fused train step computes the
parameter update in fp32 (`Optimizer._use_master_weights` path). These
wrappers exist for API parity with training scripts written against the
reference; they delegate to that machinery rather than duplicating it.
"""
from __future__ import annotations

from ....nn.layer import Layer


class MixPrecisionLayer(Layer):
    """Wrap ``layers`` for low-precision compute with fp32-mastered updates.

    Casts the wrapped model's fp32 parameters to ``dtype`` (as
    ``amp.decorate(level="O2")`` does). Gradient mastering happens in the
    optimizer (see :class:`MixPrecisionOptimizer`), which is where the
    reference's ``main_grad`` lives too once the update is computed.
    """

    def __init__(self, layers, dtype="float16"):
        super().__init__()
        from .... import amp

        self._layers = amp.decorate(layers, None, level="O2", dtype=dtype)
        self._dtype = dtype

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)


class MixPrecisionOptimizer:
    """Wrap an optimizer to update fp32 master weights from low-precision
    grads (cast to fp32 before the rule — the ``main_grad`` contract)."""

    def __init__(self, optimizer):
        self._inner = optimizer
        optimizer._use_master_weights = True

    def __getattr__(self, item):
        if item == "_inner":  # absent during deepcopy/unpickle reconstruction
            raise AttributeError(item)
        return getattr(self._inner, item)

    def step(self):
        return self._inner.step()

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)
