"""Activation recomputation (fleet.utils.recompute parity).

Reference: `python/paddle/distributed/fleet/utils/recompute.py` — re-runs the
forward of a block during backward instead of storing activations (plus RNG
state stashing so dropout masks replay identically).

TPU-native design: `jax.checkpoint` (rematerialization) — XLA re-emits the
forward ops in the backward pass; RNG replay is free because randomness is
explicit (counter-based keys are part of the traced inputs).

Granularity (the reference's ``recompute_granularity`` knob on GPT-class
models): ``"full"`` saves only the block inputs — the memory-optimal form,
and the only one that scales inside a layer-folded ``lax.scan`` (any
"saveable" intermediate is stacked across ALL layers there: saving the FFN
dot outputs of a 24-layer GPT-760M at seq 1024 batch 8 stacks to >5 GiB
and OOMs a 16 GiB v5e — measured on chip, round 5). ``"full_attn"`` /
``"core_attn"`` map to the ``dots_saveable`` policy — keep matmul outputs
(MXU work), recompute the cheap elementwise tail — the closest XLA
analogue of recomputing only the attention interior, worth it for shallow
unfolded stacks that are compute-bound rather than memory-bound.
"""
from __future__ import annotations

import jax

from ....framework.core import Tensor
from ....framework.op import defop, raw


@defop(name="recompute")
def _recompute_apply(vals, fn):
    # `fn` is a static (non-tensor) leaf: the checkpointed pure function.
    # Going through defop makes the whole block ONE tape node in eager mode
    # (jax.vjp of the checkpointed fn), mirroring the reference's single
    # RecomputeFunction autograd node.
    return fn(*vals)


def policy_for_granularity(granularity):
    """Map the reference's ``recompute_granularity`` strings to XLA remat
    policies. ``"full"`` (the reference default) -> ``None``: save only the
    block inputs. ``"full_attn"``/``"core_attn"`` (and the TPU-native alias
    ``"dots"``) -> ``dots_saveable``: keep matmul outputs, recompute the
    elementwise tail."""
    if granularity in (None, "full"):
        return None
    if granularity in ("full_attn", "core_attn", "dots"):
        return jax.checkpoint_policies.dots_saveable
    raise ValueError(
        f"unknown recompute_granularity {granularity!r}; expected 'full', "
        "'full_attn', 'core_attn' or 'dots'")


def recompute(function, *args, use_reentrant=True, preserve_rng_state=True,
              policy=None, granularity="dots", _param_owners=None, **kwargs):
    """Run `function(*args)` under rematerialization. ``policy`` (an XLA
    checkpoint policy) wins over ``granularity`` when given.

    The bare-API default stays ``"dots"`` (keep MXU outputs, recompute the
    HBM-bound elementwise tail — the right trade for a single unfolded
    block). Model configs pass their ``recompute_granularity`` explicitly,
    defaulting to the reference's ``"full"``."""
    if policy is None:
        policy = policy_for_granularity(granularity)

    tensor_args = [isinstance(a, Tensor) for a in args]
    # The block's parameters must be explicit differentiable inputs of the
    # tape node, or their grads would be lost in eager mode (they are closure
    # constants otherwise). `_param_owners` lets wrappers whose `function` is
    # a plain closure (recompute_sequential's segment runner) name the Layers
    # whose parameters the closure touches.
    if _param_owners is None:
        fn_self = getattr(function, "__self__", None)
        owner = function if hasattr(function, "named_parameters") else fn_self
        _param_owners = [owner] if owner is not None else []
    params = [p for o in _param_owners for _, p in o.named_parameters()]
    n_args = len(args)

    def pure(*vals):
        arg_vals, p_vals = vals[:n_args], vals[n_args:]
        originals = [p._value for p in params]
        try:
            for p, v in zip(params, p_vals):
                p._value = v
            wrapped = [Tensor(v) if t else v for v, t in zip(arg_vals, tensor_args)]
            out = function(*wrapped, **kwargs)
            return jax.tree_util.tree_map(
                raw, out, is_leaf=lambda x: isinstance(x, Tensor)
            )
        finally:
            for p, v in zip(params, originals):
                p._value = v

    # Tensors pass into defop intact (so grads are recorded); defop hands the
    # pure fn their raw values in the same positions.
    return _recompute_apply(list(args) + params, jax.checkpoint(pure, policy=policy))


def recompute_sequential(ctx, functions, *args, **kwargs):
    """fleet.utils.recompute_sequential parity: chunk a Sequential and
    recompute each segment."""
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else 1
    if hasattr(functions, "_sub_layers"):
        functions = list(functions._sub_layers.values())
    n = len(functions)
    per = max(1, n // max(segments, 1))
    # recompute-knob kwargs belong to recompute(), not the first layer
    # (reference contract: recompute_sequential consumes them upstream)
    rc_kwargs = {k: kwargs.pop(k)
                 for k in ("use_reentrant", "preserve_rng_state", "policy",
                           "granularity")
                 if k in kwargs}

    def run_segment(fs, first, fn_kwargs):
        def seg(*xs):
            # the first chained function receives the caller's *args and
            # **kwargs verbatim (reference variadic contract); later ones
            # take the previous function's single output
            if first:
                x_ = fs[0](*xs, **fn_kwargs)
                rest = fs[1:]
            else:
                (x_,) = xs
                rest = fs
            for f in rest:
                x_ = f(x_)
            return x_

        return seg

    cur = tuple(args)
    i, first = 0, True
    while i < n:
        seg_fns = functions[i : i + per]
        # the segment runner is a plain closure: name the layers explicitly
        # so their parameters become differentiable tape inputs (otherwise
        # their grads silently vanish in eager mode)
        owners = [f for f in seg_fns if hasattr(f, "named_parameters")]
        out = recompute(run_segment(seg_fns, first, kwargs if first else {}),
                        *cur, _param_owners=owners, **rc_kwargs)
        cur = (out,)
        first = False
        i += per
    return cur[0]


def recompute_hybrid(ctx, function, *args, **kwargs):
    """fleet.recompute_hybrid parity. In the reference this variant syncs
    RNG across the hybrid (mp/pp) groups and optionally offloads stashed
    activations to host. Under SPMD neither concern exists: randomness is an
    explicit traced key (identical on every device of the mesh by
    construction) and there are no stashed activations to offload —
    ``jax.checkpoint`` re-emits the forward in the backward program. The
    ``ctx`` dict (mp_group / offload / partition) is therefore accepted and
    only its unsupported knobs are validated."""
    if isinstance(ctx, dict) and ctx.get("partition"):
        raise NotImplementedError(
            "recompute_hybrid(partition=True): activation-partition offload "
            "has no SPMD analogue; use sharding (ZeRO-3) placement instead")
    return recompute(function, *args, **kwargs)
