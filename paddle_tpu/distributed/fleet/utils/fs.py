"""fleet.utils filesystem helpers (reference:
``python/paddle/distributed/fleet/utils/fs.py`` — LocalFS + HDFSClient over
``hadoop fs`` subprocess calls). LocalFS is fully served by the OS;
HDFSClient shells out to a ``hadoop`` binary when one exists and raises a
clear error otherwise (no cluster in this environment)."""
from __future__ import annotations

import os
import shutil
import subprocess


class ExecuteError(Exception):
    pass


class LocalFS:
    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for n in os.listdir(path):
            (dirs if os.path.isdir(os.path.join(path, n)) else files).append(n)
        return dirs, files

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def rename(self, src, dst):
        os.rename(src, dst)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def need_upload_download(self):
        return False

    def is_file(self, path):
        return os.path.isfile(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise FileExistsError(path)
        open(path, "a").close()

    def mv(self, src, dst, overwrite=False):
        if os.path.exists(dst) and not overwrite:
            raise FileExistsError(dst)
        shutil.move(src, dst)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient:
    """`hadoop fs` wrapper; needs a hadoop binary on PATH."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._bin = (os.path.join(hadoop_home, "bin", "hadoop")
                     if hadoop_home else shutil.which("hadoop"))
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]

    def _run(self, *args):
        if not self._bin or not os.path.exists(self._bin):
            raise ExecuteError(
                "HDFSClient: no hadoop binary available in this environment "
                "(offline build); use LocalFS or mount the data locally")
        p = subprocess.run([self._bin, "fs", *self._cfg, *args],
                           capture_output=True, text=True)
        if p.returncode != 0:
            raise ExecuteError(p.stderr[-500:])
        return p.stdout

    def ls_dir(self, path):
        out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rsplit("/", 1)[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def is_exist(self, path):
        try:
            self._run("-test", "-e", path)
            return True
        except ExecuteError:
            return False

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def need_upload_download(self):
        return True
