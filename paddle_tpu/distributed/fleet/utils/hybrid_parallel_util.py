"""fleet.utils.hybrid_parallel_util parity.

Reference: ``python/paddle/distributed/fleet/utils/hybrid_parallel_util.py``
— helpers DyGraph hybrid training scripts call between backward and step:
``fused_allreduce_gradients`` (manual dp grad sync when DataParallel's
reducer is bypassed, e.g. under pipeline schedules) and the
broadcast-parameters helpers used at init.

TPU-native note: under the compiled SPMD train step gradients are reduced
by GSPMD as part of the program, so these helpers matter only for EAGER
hybrid scripts ported from the reference — there they perform the real
collectives over the dp/sharding groups.
"""
from __future__ import annotations

from ....framework.core import Tensor
from ....framework.op import raw
from ... import collective as _collective


def _hcg():
    # lazy: fleet/__init__ imports this package during its own init
    from .. import get_hybrid_communicate_group

    return get_hybrid_communicate_group()


def _data_group(hcg):
    if hcg is None:
        return None
    try:
        return hcg.get_data_parallel_group()
    except Exception:
        return None


def fused_allreduce_gradients(parameter_list, hcg=None):
    """All-reduce (mean) every present gradient over the data-parallel
    group — the reference's manual dp sync point for pipeline/no-reducer
    scripts. No-op when there is no dp group or it has size 1."""
    hcg = hcg or _hcg()
    group = _data_group(hcg)
    size = getattr(group, "nranks", 1) if group is not None else 1
    if size <= 1:
        return
    for p in parameter_list:
        if getattr(p, "grad", None) is None:
            continue
        g = p.grad if isinstance(p.grad, Tensor) else Tensor(raw(p.grad))
        p.grad = _collective.all_reduce(
            g, op=_collective.ReduceOp.AVG, group=group)


def broadcast_dp_parameters(model, hcg=None):
    """Broadcast parameters from dp rank 0 (init-time sync). Under SPMD
    every rank holds the same placed value already; kept for script
    parity — re-broadcast is the identity then."""
    hcg = hcg or _hcg()
    group = _data_group(hcg)
    if group is None or getattr(group, "nranks", 1) <= 1:
        return
    for _, p in model.named_parameters():
        _collective.broadcast(p, src=0, group=group)


def broadcast_mp_parameters(model, hcg=None):
    hcg = hcg or _hcg()
    if hcg is None:
        return
    try:
        group = hcg.get_model_parallel_group()
    except Exception:
        return
    if getattr(group, "nranks", 1) <= 1:
        return
    for _, p in model.named_parameters():
        if getattr(p, "dist_spec", None):
            continue  # mp-sharded params are intentionally different
        _collective.broadcast(p, src=0, group=group)


def broadcast_sharding_parameters(model, hcg=None):
    """Broadcast over the SHARDING group (not dp — the reference syncs
    each axis with its own helper)."""
    hcg = hcg or _hcg()
    if hcg is None:
        return
    try:
        group = hcg.get_sharding_parallel_group()
    except Exception:
        return
    if getattr(group, "nranks", 1) <= 1:
        return
    for _, p in model.named_parameters():
        if getattr(p, "dist_spec", None):
            continue  # ZeRO-sharded params hold distinct shards by design
        _collective.broadcast(p, src=0, group=group)
