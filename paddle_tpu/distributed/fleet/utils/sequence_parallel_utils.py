"""Megatron-style sequence parallelism (fleet.utils.sequence_parallel_utils
parity) + Ulysses (`sep`) all-to-all helpers.

Reference capability (SURVEY.md §2.3 "Sequence parallel", §5 "Long-context"):
on the mp group, activations outside attention/MLP are sharded along the
sequence dim; explicit autograd ops `ScatterOp`/`GatherOp`/`AllGatherOp`/
`ReduceScatterOp` move between layouts, and sequence-parallel params get a
separate grad allreduce (`mark_as_sequence_parallel_parameter`,
`register_sequence_parallel_allreduce_hooks`).

TPU-native design: the layouts are PartitionSpecs — sequence dim on the `mp`
axis vs hidden dim on the `mp` axis — and the scatter/gather pairs are
`sharding_constraint` transitions; GSPMD emits the all-gather before the
matmul and the reduce-scatter after, exactly the Megatron-SP comm pattern.
The grad-sync hooks are unnecessary: parameter grads are globally correct by
construction under SPMD (documented no-ops kept for script parity).

The `sep` (Ulysses) helpers reshard between sequence-sharded and
head-sharded layouts around attention — the all-to-all emerges from the
layout change (reference: `sep` axis in topology.py).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ....framework.op import defop
from ... import mesh as _mesh
from ..layers.mpu import _data_axes


def _seq_spec(ndim: int, seq_dim: int, axis: str) -> P:
    spec = [None] * ndim
    spec[0] = _data_axes()
    spec[seq_dim] = axis
    return P(*spec)


def _full_spec(ndim: int) -> P:
    spec = [None] * ndim
    spec[0] = _data_axes()
    return P(*spec)


@defop(name="sp_scatter")
def _sp_scatter(x, seq_dim):
    return _mesh.sharding_constraint(x, _seq_spec(x.ndim, seq_dim, "mp"))


@defop(name="sp_gather")
def _sp_gather(x, seq_dim):
    return _mesh.sharding_constraint(x, _full_spec(x.ndim))


class ScatterOp:
    """Shard the sequence dim over mp (fwd scatter / bwd all-gather)."""

    @staticmethod
    def apply(x, axis=1):
        return _sp_scatter(x, axis)


class GatherOp:
    """Replicate the sequence dim (fwd all-gather / bwd scatter)."""

    @staticmethod
    def apply(x, axis=1):
        return _sp_gather(x, axis)


class AllGatherOp(GatherOp):
    pass


class ReduceScatterOp(ScatterOp):
    pass


def scatter(x, axis=1):
    return ScatterOp.apply(x, axis)


def all_gather(x, axis=1):
    return GatherOp.apply(x, axis)


def mark_as_sequence_parallel_parameter(parameter):
    """Grad sync for SP params is implicit under SPMD; keep the marker."""
    parameter.sequence_parallel = True


def is_sequence_parallel_parameter(parameter) -> bool:
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1, fuse=False):
    """No-op under SPMD: XLA produces globally-reduced grads. Kept for parity."""
    return model


# ------------------------------------------------------- sep / Ulysses layout
@defop(name="sep_to_heads")
def sep_reshard_to_heads(x, head_dim_axis):
    """[b, s/sep, h, d] → heads sharded on sep: the layout flip IS the
    all-to-all (lax.all_to_all under shard_map; GSPMD reshard under pjit)."""
    m = _mesh.get_global_mesh()
    if m is None or "sep" not in m.shape or m.shape["sep"] == 1:
        return x
    spec = [None] * x.ndim
    spec[0] = _data_axes()
    spec[head_dim_axis] = "sep"
    return _mesh.sharding_constraint(x, P(*spec))


@defop(name="sep_to_sequence")
def sep_reshard_to_sequence(x, seq_dim=1):
    m = _mesh.get_global_mesh()
    if m is None or "sep" not in m.shape or m.shape["sep"] == 1:
        return x
    spec = [None] * x.ndim
    spec[0] = _data_axes()
    spec[seq_dim] = "sep"
    return _mesh.sharding_constraint(x, P(*spec))
