from .recompute_helper import recompute, recompute_sequential  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401
