from . import mix_precision_utils  # noqa: F401
from .recompute_helper import (  # noqa: F401
    recompute, recompute_hybrid, recompute_sequential,
)
from . import sequence_parallel_utils  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401
from . import hybrid_parallel_util  # noqa: E402,F401
