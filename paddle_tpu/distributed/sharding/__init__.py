"""paddle.distributed.sharding parity: `group_sharded_parallel`.

Reference (SURVEY.md §2.3 "Sharding (ZeRO-1/2/3)",
`python/paddle/distributed/sharding/group_sharded.py`): wraps model+optimizer
into GroupShardedStage1/2/3 engines with explicit gather/scatter hooks.

TPU-native: the stages are placements (see meta_parallel/sharding.py) —
  level "os"     → ZeRO-1: optimizer states sharded
  level "os_g"   → ZeRO-2: + grads (implicit inside the compiled step)
  level "p_g_os" → ZeRO-3: + parameters sharded (FSDP)
"""
from __future__ import annotations

from ..fleet import HybridParallelOptimizer, shard_model_parameters

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def group_sharded_parallel(
    model,
    optimizer,
    level: str = "os_g",
    scaler=None,
    group=None,
    offload=False,
    sync_buffers=False,
    buffer_max_size=2**23,
    segment_size=2**20,
    sync_comm=False,
    dp_group=None,
    exclude_layer=None,
):
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {list(_LEVELS)}, got {level!r}")
    stage = _LEVELS[level]
    shard_model_parameters(model, fsdp=(stage == 3))
    if not isinstance(optimizer, HybridParallelOptimizer):
        optimizer = HybridParallelOptimizer(optimizer)
    # the reference fuses gradient comm into `buffer_max_size`-byte buffers
    # (GroupShardedStage2 _comm_buffer_size); carry that granularity onto
    # the explicit grad-comm bucket size so a ported script that tuned it
    # keeps its comm pattern when it opts into strategy.grad_comm
    from ..fleet import fleet_strategy

    strat = fleet_strategy()
    if strat is not None and buffer_max_size:
        strat.grad_comm_configs["bucket_mb"] = float(buffer_max_size) / 2 ** 20
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Checkpoint a sharded model (gathers happen on host materialization)."""
    from ...framework.io_state import save

    save(model.state_dict(), output + ".pdparams" if not output.endswith(".pdparams") else output)
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
