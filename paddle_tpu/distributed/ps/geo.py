"""Async push/pull parameter-server tiers, TPU-reshaped.

Reference capability (SURVEY.md §1/§2.3 "Parameter server"): the async PS
modes in ``paddle/fluid/distributed/ps/service/communicator/`` — geo-SGD
(each trainer trains on a full local copy and exchanges accumulated deltas
every k steps) — and the heter-PS cache tiers
(``paddle/fluid/framework/fleet/heter_ps/``: tables too large for device
memory live in host RAM/SSD, hot rows are staged onto the accelerator).

TPU-native reshape — two honest pieces, no server processes:

- ``GeoSGDCommunicator``: geo-SGD with SERVERLESS peer merge. Each worker
  keeps a base snapshot of the table; ``push()`` publishes (local - base)
  for touched rows to a DeltaStore, ``pull()`` folds every peer's new
  deltas into the local copy. Additive delta merge is exactly geo-SGD's
  server-side rule, so the store can be a dumb KV (in-process dict for
  SPMD tests, the C++ TCPStore across real processes) instead of a brpc
  service. Staleness semantics match the reference: between syncs workers
  drift, merged state is the sum of everyone's local progress.

- ``HostOffloadedTable``: the heter-PS capability for ONE chip — rows live
  in host RAM (numpy), ``pull(ids)`` stages the unique hot rows to device
  for the step, ``push(ids, grads)`` applies rowwise-AdaGrad on host (the
  classic PS sparse optimizer). HBM holds only the working set, so the
  table can exceed device memory by orders of magnitude.

Deliberately absent (documented non-goals): brpc transport, SSD cache
tier, server-side fused optimizers — the synchronous mesh-sharded
``ShardedEmbeddingTable`` (``ps/__init__.py``) is the first-choice design
on TPU; these tiers exist for tables that outgrow the mesh.
"""
from __future__ import annotations

import io
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "LocalDeltaStore",
    "TCPDeltaStore",
    "GeoSGDCommunicator",
    "HostOffloadedTable",
]


def _pack(ids: np.ndarray, delta: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, ids=ids, delta=delta)
    return buf.getvalue()


def _unpack(blob: bytes) -> Tuple[np.ndarray, np.ndarray]:
    with np.load(io.BytesIO(blob)) as z:
        return z["ids"], z["delta"]


class LocalDeltaStore:
    """In-process DeltaStore: one dict shared by every communicator in the
    process (the SPMD/test transport). Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._blobs: Dict[str, bytes] = {}
        self._rounds: Dict[Tuple[str, int], int] = {}  # (table, worker) -> n

    def publish(self, table: str, worker: int, blob: bytes) -> int:
        with self._lock:
            n = self._rounds.get((table, worker), 0)
            self._blobs[f"{table}/{worker}/{n}"] = blob
            self._rounds[(table, worker)] = n + 1
            return n

    def rounds_of(self, table: str, worker: int) -> int:
        with self._lock:
            return self._rounds.get((table, worker), 0)

    def fetch(self, table: str, worker: int, rnd: int) -> Optional[bytes]:
        with self._lock:
            return self._blobs.get(f"{table}/{worker}/{rnd}")


class TCPDeltaStore:
    """DeltaStore over the C++ TCPStore (``runtime.TCPStore``): the
    cross-process transport. Keys: ``geo/{table}/{worker}/{round}`` carry
    the delta blob; ``geo/{table}/{worker}/n`` counts published rounds
    (via the store's atomic add)."""

    def __init__(self, store):
        self._s = store

    def publish(self, table: str, worker: int, blob: bytes) -> int:
        n = self._s.add(f"geo/{table}/{worker}/n", 1) - 1
        self._s.set(f"geo/{table}/{worker}/{n}", blob)
        return n

    def rounds_of(self, table: str, worker: int) -> int:
        # atomic add of 0 reads the counter without waiting on a set
        return self._s.add(f"geo/{table}/{worker}/n", 0)

    def fetch(self, table: str, worker: int, rnd: int) -> Optional[bytes]:
        try:
            return bytes(self._s.get(f"geo/{table}/{worker}/{rnd}", timeout=30.0))
        except Exception:
            return None


class GeoSGDCommunicator:
    """Geo-SGD async table sync (reference: geo mode of
    ``distributed/ps/service/communicator``), serverless.

    Usage per worker::

        comm = GeoSGDCommunicator(table, store, worker_id=r, num_workers=W,
                                  sync_every=k)
        for step, batch in enumerate(data):
            rows = train_on(comm.table, batch)     # local dense/sparse math
            comm.touch(rows)                       # rows this worker changed
            comm.step()                            # pushes+pulls every k

    ``table`` is mutated IN PLACE (numpy [vocab, dim]); after a sync the
    local copy equals base + every worker's published deltas (applied
    additively, the geo merge rule).
    """

    def __init__(self, table: np.ndarray, store, worker_id: int,
                 num_workers: int, sync_every: int = 8, name: str = "table"):
        self.table = table
        self._base = table.copy()
        self._store = store
        self.worker_id = int(worker_id)
        self.num_workers = int(num_workers)
        self.sync_every = max(1, int(sync_every))
        self.name = name
        self._touched: set = set()
        self._step = 0
        self._seen_rounds = [0] * self.num_workers

    def touch(self, ids) -> None:
        self._touched.update(int(i) for i in np.atleast_1d(np.asarray(ids)).ravel())

    def step(self) -> bool:
        self._step += 1
        if self._step % self.sync_every != 0:
            return False
        self.sync()
        return True

    def push(self) -> None:
        ids = np.fromiter(sorted(self._touched), dtype=np.int64,
                          count=len(self._touched))
        delta = (self.table[ids] - self._base[ids]) if len(ids) else \
            np.zeros((0, self.table.shape[1]), self.table.dtype)
        rnd = self._store.publish(self.name, self.worker_id, _pack(ids, delta))
        # fold our published delta into base NOW and mark it seen — pull()
        # computes local drift as (table - base); leaving the pushed delta
        # in the drift while also fetching it back would double-count it
        if len(ids):
            np.add.at(self._base, ids, delta)
        self._seen_rounds[self.worker_id] = rnd + 1
        self._touched.clear()

    def pull(self) -> None:
        """Fold every peer's unseen deltas (and our own published ones) into
        base, then re-apply our local unpublished drift on top."""
        local_drift = self.table - self._base
        for w in range(self.num_workers):
            upto = self._store.rounds_of(self.name, w)
            for rnd in range(self._seen_rounds[w], upto):
                blob = self._store.fetch(self.name, w, rnd)
                if blob is None:
                    continue
                ids, delta = _unpack(blob)
                if len(ids):
                    np.add.at(self._base, ids, delta.astype(self._base.dtype))
            self._seen_rounds[w] = upto
        np.copyto(self.table, self._base + local_drift)

    def sync(self) -> None:
        self.push()
        self.pull()


class HostOffloadedTable:
    """Heter-PS host-memory tier for one accelerator: a [vocab, dim] table
    in host RAM with device-staged lookups and host-side rowwise-AdaGrad
    updates (reference: ``heter_ps`` HBM/host cache,
    ``CtrDymfAccessor``-style sparse optimizer).

    ``pull(ids)`` -> device array of the unique rows (plus the inverse map
    to expand per-position); ``push(unique_ids, row_grads)`` applies
    AdaGrad on host. The device never holds more than the batch's working
    set. Optionally wired to a GeoSGDCommunicator for async multi-worker
    sync of the host table.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 lr: float = 0.05, initializer=None, dtype="float32",
                 seed: int = 0, geo: Optional[GeoSGDCommunicator] = None):
        rng = np.random.default_rng(seed)
        scale = 1.0 / np.sqrt(embedding_dim)
        self.table = (initializer if initializer is not None else
                      rng.uniform(-scale, scale,
                                  (num_embeddings, embedding_dim))).astype(dtype)
        self.lr = float(lr)
        self._g2 = np.zeros(num_embeddings, dtype)  # AdaGrad row accumulators
        self.geo = geo
        if geo is not None:
            geo.table = self.table  # share storage

    def pull(self, ids):
        """ids: int array [...]; returns (device rows [n_unique, dim],
        unique ids [n_unique], inverse map with ids' shape)."""
        import jax.numpy as jnp

        flat = np.asarray(ids).ravel()
        uniq, inv = np.unique(flat, return_inverse=True)
        rows = jnp.asarray(self.table[uniq])
        return rows, uniq, inv.reshape(np.asarray(ids).shape)

    def lookup(self, ids):
        """Convenience: full [..., dim] device gather (pull + expand)."""
        import jax.numpy as jnp

        rows, _, inv = self.pull(ids)
        return jnp.take(rows, jnp.asarray(inv), axis=0)

    def push(self, unique_ids, row_grads) -> None:
        """Rowwise AdaGrad: g2[i] += mean(grad_i^2); row -= lr*g/sqrt(g2+eps).
        ``row_grads`` aligns with ``unique_ids`` (sum-reduced per unique id,
        as returned by a grad of the pull output)."""
        ids = np.asarray(unique_ids).ravel()
        g = np.asarray(row_grads, self.table.dtype)
        self._g2[ids] += (g * g).mean(axis=-1)
        self.table[ids] -= (
            self.lr * g / np.sqrt(self._g2[ids] + 1e-10)[:, None])
        if self.geo is not None:
            self.geo.touch(ids)
            self.geo.step()

    def state_dict(self) -> Dict[str, np.ndarray]:
        return {"table": self.table, "g2": self._g2}

    def set_state_dict(self, s) -> None:
        np.copyto(self.table, np.asarray(s["table"], self.table.dtype))
        np.copyto(self._g2, np.asarray(s["g2"], self._g2.dtype))
