"""Parameter-server capability, TPU-reshaped.

Reference (SURVEY.md §1 "Parameter-server stack", §2.3 "Parameter server"):
`paddle/fluid/distributed/ps/` — dense/sparse tables on dedicated server
processes over brpc, async/geo-SGD, heter-PS with HBM/SSD caches, driven by
`fleet.init(role)` PS mode (`python/paddle/distributed/ps/the_one_ps.py`).

What PS-mode actually buys the reference is ONE capability: embedding
tables too large for a single accelerator, updated sparsely by many
workers. The TPU-native equivalent is not a server process — it is a
MESH-SHARDED table: rows are partitioned over the device mesh
(`ShardedEmbeddingTable`), lookups become GSPMD-inserted collectives over
ICI, and updates are the same SPMD optimizer step every other parameter
takes (sparse-gradient row updates arrive as dense-with-zeros grads that
XLA keeps sharded). There are no servers to start, so the PS role-control
API (`is_first_worker`, `init_server`, `run_server`, `init_worker`,
`stop_worker`, barriers) is provided as working no-ops/logical equivalents
so PS-mode training scripts run unchanged under the collective runtime.

The async tiers live in ``ps.geo``: ``GeoSGDCommunicator`` (geo-SGD
delta exchange over the TCPStore — serverless peer merge) and
``HostOffloadedTable`` (heter-PS host-RAM tier with device-staged hot
rows + rowwise AdaGrad). Deliberately absent (documented non-goals, not
gaps on TPU): brpc transport and the SSD cache tier — XLA's synchronous
SPMD is the first-choice consistency model; the async tiers exist for
tables that outgrow the mesh.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...framework.core import Tensor
from ...framework.op import defop, raw
from ...nn import Layer
from ...nn import initializer as I
from ...nn.layer import Parameter
from .. import mesh as _mesh

from .geo import (  # noqa: E402  (async PS tiers — see module docstring)
    GeoSGDCommunicator,
    HostOffloadedTable,
    LocalDeltaStore,
    TCPDeltaStore,
)

__all__ = [
    "ShardedEmbeddingTable",
    "sparse_embedding",
    "RoleMakerBase",
    "table_shard_info",
    "GeoSGDCommunicator",
    "HostOffloadedTable",
    "LocalDeltaStore",
    "TCPDeltaStore",
]


def _table_axis() -> Optional[str]:
    """Mesh axis carrying table rows: widest of sharding/mp/dp."""
    m = _mesh.get_global_mesh()
    if m is None:
        return None
    best, width = None, 1
    for name in ("sharding", "mp", "dp"):
        if m.shape.get(name, 1) > width:
            best, width = name, m.shape[name]
    return best


class ShardedEmbeddingTable(Layer):
    """A vocab-row-sharded embedding table — the PS "distributed table".

    Rows live partitioned over the table mesh axis (each device holds
    vocab/N rows); a lookup is a sharded gather for which GSPMD inserts the
    exact comm the reference routes through its PS RPC (but over ICI, inside
    the compiled step). Works as a drop-in Embedding for rec-sys-scale
    vocabularies.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx=None, weight_attr=None, dtype="float32",
                 name=None):
        super().__init__()
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [self.num_embeddings, self.embedding_dim],
            attr=weight_attr,
            dtype=dtype,
            default_initializer=I.XavierNormal(),
        )
        ax = _table_axis()
        if ax is not None and self.num_embeddings % _mesh.mesh_axis_size(ax) == 0:
            self.weight.dist_spec = P(ax)
            self.weight.is_distributed = True
            self.weight._rebind(
                _mesh.sharding_constraint(raw(self.weight), P(ax))
            )

    def forward(self, ids):
        return _sharded_lookup(
            ids, self.weight, padding_idx=self.padding_idx
        )

    def shard_info(self):
        return table_shard_info(self.weight)


@defop(name="sharded_embedding_lookup")
def _sharded_lookup(ids, table, padding_idx=None):
    out = jnp.take(table, ids, axis=0)
    if padding_idx is not None:
        out = out * (ids != padding_idx)[..., None].astype(out.dtype)
    return out


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32", name=None):
    """`paddle.static.nn.sparse_embedding` parity — the PS-mode lookup op.

    Builds (once per call site, like static.nn layers) a ShardedEmbeddingTable
    and applies it. `entry` (frequency-gated rows) is accepted and ignored:
    row admission policies exist to bound PS server memory, which row
    sharding already bounds deterministically.
    """
    from ...static.nn import _auto, _get

    key = _auto("sparse_embedding", name)
    table = _get(
        key, lambda: ShardedEmbeddingTable(size[0], size[1], padding_idx,
                                           weight_attr=param_attr,
                                           dtype=dtype)
    )
    return table(input)


def table_shard_info(weight) -> dict:
    """Placement report for a sharded table (PS `print_table_stats` role)."""
    v = raw(weight)
    sharding = getattr(v, "sharding", None)
    n_shards = 1
    ax = None
    spec = getattr(sharding, "spec", None)
    if spec:
        m = _mesh.get_global_mesh()
        names = [s for s in jax.tree_util.tree_leaves(list(spec)) if s]
        ax = names[0] if names else None
        if m is not None and ax in m.shape:
            n_shards = m.shape[ax]
    return {
        "global_rows": int(v.shape[0]),
        "dim": int(v.shape[1]),
        "num_shards": n_shards,
        "rows_per_shard": int(v.shape[0]) // max(n_shards, 1),
        "axis": ax,
        "bytes_per_shard": int(v.size * v.dtype.itemsize) // max(n_shards, 1),
    }


class RoleMakerBase:
    """PS role protocol, collective-runtime semantics: every process is a
    worker; there are no servers (tables are mesh-sharded)."""

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False

    def is_first_worker(self) -> bool:
        from .. import get_rank

        return get_rank() == 0

    def worker_num(self) -> int:
        from .. import get_world_size

        return get_world_size()

    def server_num(self) -> int:
        return 0

    def worker_index(self) -> int:
        from .. import get_rank

        return get_rank()
