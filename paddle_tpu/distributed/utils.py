"""paddle.distributed.utils parity (``python/paddle/distributed/utils/``):
helper surface re-exporting the MoE global scatter/gather ops plus launch
helpers used by reference scripts."""
from __future__ import annotations

from ..incubate.moe import global_gather, global_scatter  # noqa: F401


def get_cluster(node_ips=None, node_ip=None, trainer_endpoints=None,
                device_mode=None, devices_per_proc=None):
    raise NotImplementedError(
        "get_cluster is a GPU-launcher internal; TPU jobs negotiate ranks "
        "through paddle_tpu.distributed.launch (TCPStore rendezvous)"
    )


def get_host_name_ip():
    import socket

    host = socket.gethostname()
    try:
        return host, socket.gethostbyname(host)
    except OSError:
        return host, "127.0.0.1"
