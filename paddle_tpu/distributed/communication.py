"""paddle.distributed.communication path parity.

Reference: ``python/paddle/distributed/communication/`` — the package where
upstream implements the user-level collective API (``all_reduce`` etc.) and
its ``stream.*`` variants (explicit comm-stream control + ``sync_op``).

Here the implementations live in :mod:`paddle_tpu.distributed.collective`
(one module — there are no user-managed comm streams on TPU, SURVEY.md §2.3
"Comm APIs"); this module re-exports them so code importing the reference's
``paddle.distributed.communication.stream`` path keeps working.
"""
from .collective import (  # noqa: F401
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    broadcast_object_list,
    gather,
    get_backend,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    scatter_object_list,
    send,
    stream,
    wait,
)
