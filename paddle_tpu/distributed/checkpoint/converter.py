"""Topology-independent checkpoint form (the reference's checkpoint
converter capability, ``python/paddle/distributed/auto_parallel/static/
converter.py``: re-slice checkpoints across parallel configurations).

TPU-native shape of the problem: most placement differences need NO
conversion at all (sharding is placement over the same global arrays, and
orbax restores onto the target sharding directly). The one structural
difference is pipeline parallelism: ``SpmdPipeline`` absorbs its blocks'
parameters into layer-stacked arrays (``gpt.decoder.attn__qkv_proj__weight``
with leading layer dim, interleaved stage-major order) where the plain
model keeps per-layer entries (``gpt.decoder.3.attn.qkv_proj.weight``).

``canonical_state_dict`` therefore explodes stacked entries to the plain
per-layer names (undoing the interleaved ``_layer_order``) with lazy jax
slices (no host materialization), and ``apply_canonical`` re-stacks them
for whatever pipeline layout the LIVE model uses — so a checkpoint saved
under dp2 x mp2 x pp2 restores under sharding8 (or any other config) and
vice versa. Optimizer accumulators are keyed by the param's STRUCTURED
state-dict path (never a run-local auto ``param_N`` name mismatch: the
index follows the optimizer's own parameter list) and explode/restack
alongside their params; scalar accumulators (Adam beta powers) replicate
per layer on save and collapse on load. Missing keys at restore RAISE —
silently resuming on fresh inits is worse than failing.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

from ...framework.core import Tensor
from ...framework.op import raw

OPT = "__opt__."


def _as_value(v):
    return raw(v) if isinstance(v, Tensor) else v


def _stacked_map(model) -> Dict[str, tuple]:
    """Exact lookup: state-dict key of a pipeline-stacked param/buffer ->
    (pipe, canonical template name). Built from each SpmdPipeline's own
    registration (attr = template name with '.' -> '__', buffers suffixed
    '_stacked'), not by string-sniffing — a dot-free template name like
    'weight' maps correctly."""
    from ..fleet.meta_parallel.pipeline_parallel import SpmdPipeline

    out = {}
    for path, sub in model.named_sublayers(include_self=True):
        if not isinstance(sub, SpmdPipeline):
            continue
        pfx = path + "." if path else ""
        for n, _ in sub._template_holder[0].named_parameters():
            out[pfx + n.replace(".", "__")] = (path, sub, n)
        for n, _ in sub._template_holder[0].named_buffers():
            out[pfx + n.replace(".", "__") + "_stacked"] = (path, sub, n)
    return out


def _param_paths(model, optimizer=None) -> Dict[str, str]:
    """optimizer-facing param NAME -> state-dict path, via object identity.
    The ``param_{i}`` fallback indexes the OPTIMIZER's parameter list (that
    is how Optimizer.state_dict names them), never the model's order."""
    by_id = {id(v): k for k, v in model.state_dict().items()}
    plist = (optimizer._parameter_list if optimizer is not None
             else [p for _, p in model.named_parameters()])
    out = {}
    for i, p in enumerate(plist):
        name = p.name or f"param_{i}"
        if id(p) in by_id:
            out[name] = by_id[id(p)]
    return out


def _split_opt_key(key):
    """'<pname>.<acc>' -> (pname, acc); accumulator suffix has no dots."""
    pname, _, acc = key.rpartition(".")
    return pname, acc


def _layer_key(path, layer, tmpl):
    return f"{path}.{layer}.{tmpl}" if path else f"{layer}.{tmpl}"


def canonical_state_dict(model, optimizer=None, abstract: bool = False):
    """Flat topology-independent snapshot of model (+ optimizer) state.
    Values stay jax arrays (stacked entries become lazy device-side layer
    slices) so the orbax writer keeps its shard-aware, async-capable path.

    ``abstract=True`` emits ShapeDtypeStructs for the exploded per-layer
    entries instead of executing the slices — restore-target construction
    must not allocate a second full copy of every stacked param on device
    in the memory-tight resume path."""
    stacked_keys = _stacked_map(model)
    out: Dict[str, Any] = {}

    def explode(canon_prefix, pipe_entry, value, suffix=""):
        path, pipe, tmpl = pipe_entry
        v = _as_value(value)
        is_stacked = getattr(v, "ndim", 0) >= 1 and v.shape[0] == pipe.num_layers
        if is_stacked and abstract:
            slice_t = jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
        for i, layer in enumerate(pipe._layer_order):
            out[canon_prefix + _layer_key(path, layer, tmpl) + suffix] = (
                (slice_t if abstract else v[i]) if is_stacked else v)

    for key, val in model.state_dict().items():
        if key in stacked_keys:
            explode("", stacked_keys[key], val)
        else:
            out[key] = val

    if optimizer is not None:
        if hasattr(optimizer, "functional_states"):
            optimizer.functional_states()  # materialize accumulators
        name_to_path = _param_paths(model, optimizer)
        for key, val in optimizer.state_dict().items():
            if key == "LR_Scheduler":
                out[OPT + key] = val
                continue
            pname, acc = _split_opt_key(key)
            path_key = name_to_path.get(pname, pname)
            if path_key in stacked_keys:
                explode(OPT, stacked_keys[path_key], val, suffix=f".{acc}")
            else:
                out[OPT + path_key + f".{acc}"] = val

    return out


def restore_canonical(path, model, optimizer=None) -> Dict[str, Any]:
    """Orbax restore of a canonical checkpoint, sharded where possible: the
    live canonical tree provides shape/dtype/sharding targets, so
    non-stacked arrays restore straight onto their current placements (no
    full host materialization); a saved-vs-live tree mismatch raises in
    orbax rather than resuming silently on fresh inits. (User payloads that
    exist only on disk — ElasticManager's ``extra`` — live in a sidecar
    checkpoint precisely so this target never has to guess their shapes.)

    Restore-anywhere (distributed/reshard.py): when the checkpoint's
    manifest carries a layout record, each leaf is read onto a
    memory-bounded READ spec on the live mesh (the source shard granularity
    re-expressed with target axes — every device reads ~its source-local
    bytes) and the planned slice/all-to-all/gather steps carry it to the
    live placement. A restore failure on a checkpoint WITHOUT a layout
    record raises the clear legacy-format diagnosis instead of a shape
    mismatch deep in jax/orbax.
    """
    import time as _time

    from . import _checkpointer
    from ..reshard import (apply_steps, legacy_error, plan_restore_spec,
                           plan_same_mesh, read_layout_record,
                           record_plan_metrics)

    live = canonical_state_dict(model, optimizer, abstract=True)
    rec = read_layout_record(path)
    rec_mesh, rec_leaves = rec if rec else (None, {})
    t0 = _time.perf_counter()
    pending = {}  # key -> (plan, live mesh): collective steps after the read
    amb_mesh = next(
        (sh.mesh for v in live.values()
         if (sh := getattr(_as_value(v), "sharding", None)) is not None
         and getattr(sh, "mesh", None) is not None), None)

    def to_target(k, v):
        if isinstance(v, jax.ShapeDtypeStruct):
            return v  # exploded per-layer entry: restored unsharded, then
            #           restacked onto the live sharding by apply_canonical
        v = _as_value(v)
        if not (hasattr(v, "shape") and hasattr(v, "dtype")):
            return v
        sh = getattr(v, "sharding", None)
        lay = rec_leaves.get(k)
        if (rec_mesh is not None and lay is not None
                and getattr(sh, "mesh", None) is not None
                and getattr(sh, "spec", None) is not None
                and tuple(lay.shape) == tuple(v.shape)):
            read = plan_restore_spec(lay, rec_mesh, sh.mesh, sh.spec)
            sizes = {n: int(sh.mesh.shape[n]) for n in sh.mesh.axis_names}
            plan = plan_same_mesh(v.shape, v.dtype, read, sh.spec, sizes,
                                  key=k)
            if plan.steps:
                pending[k] = (plan, sh.mesh)
                return jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=type(sh)(sh.mesh, read))
        if (getattr(sh, "mesh", None) is None and amb_mesh is not None
                and isinstance(v, jax.Array)):
            # un-meshed device leaf (fresh scalar accumulator) in a meshed
            # model: restore it replicated on the ambient mesh — restoring
            # committed to its current single device would hand the next
            # jitted step arrays on conflicting device sets
            sh = jax.sharding.NamedSharding(amb_mesh,
                                            jax.sharding.PartitionSpec())
        return jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=sh)

    target = {k: to_target(k, v) for k, v in live.items()}
    try:
        with _checkpointer() as ckptr:
            restored = ckptr.restore(path, target)
    except (ValueError, TypeError, KeyError) as e:
        if rec is None:
            raise legacy_error(path, e) from e
        raise
    if pending:
        fence = 0
        for k, (plan, mesh) in pending.items():
            restored[k] = apply_steps(restored[k], plan, mesh,
                                      fence_base=fence)
            fence += len(plan.steps)
        record_plan_metrics([p for p, _ in pending.values()], what="restore",
                            seconds=_time.perf_counter() - t0)
    return restored


class _StackPieces:
    """Deferred layer-restack: materialized only once the LIVE value (and
    its sharding) is known, so the stack happens on device with the target
    sharding instead of a full host copy of every stacked param."""

    def __init__(self, pieces):
        self.pieces = pieces


def _put_like(new, old_val):
    """Materialize `new` with the live value's placement (keeps ZeRO/mp
    shardings across the restore instead of silently replicating). A
    device_put failure propagates — restoring a param replicated when the
    live layout says sharded is a silent HBM blowup, not a fallback."""
    dtype = getattr(old_val, "dtype", None)
    sh = getattr(old_val, "sharding", None)
    sharded = sh is not None and getattr(sh, "mesh", None) is not None
    if isinstance(new, _StackPieces):
        pieces = [jax.numpy.asarray(p, dtype=dtype) for p in new.pieces]
        if sharded:
            # compiled stack with the live out-sharding: per-layer restored
            # shards flow to the stacked placement without a host round-trip
            return jax.jit(
                lambda *xs: jax.numpy.stack(xs, axis=0), out_shardings=sh
            )(*pieces)
        return jax.numpy.stack(pieces, axis=0)
    arr = jax.numpy.asarray(new, dtype=dtype)
    if sharded:
        return jax.device_put(arr, sh)
    return arr


def apply_canonical(model, canonical: Dict[str, Any], optimizer=None):
    """Restore a canonical snapshot into the LIVE model/optimizer layout
    (re-stacking for whatever pipelines the model uses). Raises KeyError
    listing anything the checkpoint is missing."""
    stacked_keys = _stacked_map(model)
    missing = []

    def assemble(pipe_entry, template_val, prefix="", suffix=""):
        path, pipe, tmpl = pipe_entry
        tv = _as_value(template_val)
        is_stacked = getattr(tv, "ndim", 0) >= 1 and tv.shape[0] == pipe.num_layers
        pieces = []
        for layer in pipe._layer_order:
            k = prefix + _layer_key(path, layer, tmpl) + suffix
            if k not in canonical:
                missing.append(k)
                return None
            pieces.append(_as_value(canonical[k]))
        if not is_stacked:
            return pieces[0]  # scalar accumulator replicated per layer
        return _StackPieces(pieces)

    updates = []
    for key, t in model.state_dict().items():
        if key in stacked_keys:
            new = assemble(stacked_keys[key], t)
        elif key in canonical:
            new = canonical[key]
        else:
            missing.append(key)
            new = None
        if new is not None:
            updates.append((t, new))

    opt_restored = {}
    if optimizer is not None:
        if hasattr(optimizer, "functional_states"):
            optimizer.functional_states()
        name_to_path = _param_paths(model, optimizer)
        for key, val in optimizer.state_dict().items():
            if key == "LR_Scheduler":
                if OPT + key in canonical:
                    opt_restored[key] = canonical[OPT + key]
                continue
            pname, acc = _split_opt_key(key)
            path_key = name_to_path.get(pname, pname)
            if path_key in stacked_keys:
                new = assemble(stacked_keys[path_key], val,
                               prefix=OPT, suffix=f".{acc}")
            else:
                new = canonical.get(OPT + path_key + f".{acc}")
                if new is None:
                    missing.append(OPT + path_key + f".{acc}")
            if new is not None:
                opt_restored[key] = Tensor(_put_like(new, _as_value(val)))

    if missing:
        raise KeyError(
            "checkpoint is missing entries for the live model/optimizer "
            f"(stale or pre-canonical format?): {sorted(set(missing))[:8]}"
            f"{' ...' if len(set(missing)) > 8 else ''}")

    for t, new in updates:
        t._rebind(_put_like(new, t._value))
    if optimizer is not None and opt_restored:
        optimizer.set_state_dict(opt_restored)
    return model
