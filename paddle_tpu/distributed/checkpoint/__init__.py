"""Distributed checkpointing — sharded, async, topology-independent.

Reference capability (SURVEY.md §5 "Checkpoint/resume"): per-rank sharded
state dicts (GroupSharded), `fleet.save_persistables`, and the auto-parallel
**checkpoint converter** (`auto_parallel/static/converter.py`) that re-slices
checkpoints across different parallel configs.

TPU-native design: Orbax. Every host writes its local shards; metadata maps
global shape → shards; on load, passing the *target* shardings re-slices
automatically — the whole converter subsystem becomes a load argument
(SURVEY.md §7 "Hard parts": checkpoint re-sharding). Async save overlaps
serialization with training steps.
"""
from __future__ import annotations

import os
import shutil
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from ... import observability as _obs
from ...framework.core import Tensor
from ...framework.op import raw
from . import manifest as _manifest

#: suffix for in-flight (uncommitted) checkpoint directories; a crash at any
#: point leaves either the old committed dir or a *.ptsave-tmp leftover —
#: never a half-written dir under the final name
TMP_SUFFIX = ".ptsave-tmp"


def _exportable(arr):
    """Orbax-serializable view of one array. In a multiprocess runtime
    orbax's type handler rejects fully-addressable ("host local")
    jax.Arrays — it only accepts single-process arrays or global multihost
    arrays — so per-rank local state is exported as numpy instead. Global
    (cross-host sharded) arrays pass through untouched."""
    if (jax.process_count() > 1 and isinstance(arr, jax.Array)
            and arr.is_fully_addressable):
        return np.asarray(arr)
    return arr


def _to_arrays(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            out[k] = _exportable(raw(v))
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        elif isinstance(v, np.generic):
            # orbax's StandardCheckpointHandler accepts ndarray but not
            # numpy scalar types (np.int64 et al. fail its type check)
            out[k] = np.asarray(v)
        elif isinstance(v, jax.Array):
            out[k] = _exportable(v)
        else:
            out[k] = v
    return out


def _mp_options():
    """Per-process orbax multiprocessing config.

    Every caller here saves its OWN (host-local) state dict to its OWN
    path — the elastic per-rank layout — so in a multiprocess runtime each
    rank must be its own primary with private barriers. Orbax's default
    (primary host 0, global barriers) would never finalize rank>0's
    checkpoint and would deadlock rank 0 unless every rank saved in
    lockstep."""
    import orbax.checkpoint as ocp

    if jax.process_count() <= 1:
        return ocp.options.MultiprocessingOptions()
    me = jax.process_index()
    return ocp.options.MultiprocessingOptions(
        primary_host=None, active_processes={me},
        barrier_sync_key_prefix=f"rank{me}")


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer(multiprocessing_options=_mp_options())


def _dir_bytes(root: str) -> int:
    total = 0
    for dirpath, _, filenames in os.walk(root):
        for fn in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                pass
    return total


def _record_save(path: str, seconds: float) -> None:
    if not _obs.enabled():
        return  # skip the directory walk entirely when telemetry is off
    nbytes = _dir_bytes(path)
    _obs.observe("checkpoint_save_seconds", seconds)
    _obs.inc("checkpoint_save_bytes_total", nbytes)
    _obs.event("checkpoint_save", path=path, seconds=round(seconds, 6),
               bytes=nbytes)
    _obs.record_span("ckpt_save", dur_s=seconds, path=path, bytes=nbytes)


class _AtomicCommit:
    """Turns a finished body write under the tmp name into a committed
    checkpoint: chaos fault point -> checksum manifest -> atomic rename ->
    parent-dir fsync. A kill at ANY point leaves either the previous
    committed dir or a *.ptsave-tmp leftover — never a torn final dir."""

    def __init__(self, tmp: str, final: str, meta: Optional[dict] = None):
        self.tmp = tmp
        self.final = final
        self.meta = meta

    def run(self):
        from ...testing import chaos

        chaos.on_commit(self.tmp, self.final)
        _manifest.write_manifest(self.tmp, meta=self.meta)
        if os.path.exists(self.final):
            shutil.rmtree(self.final)
        os.replace(self.tmp, self.final)
        _manifest.fsync_dir(os.path.dirname(self.final))
        chaos.after_commit(self.final)


class PendingSave:
    """Handle for an in-flight async save. The commit (manifest + rename)
    happens on `wait_until_finished()` — until then the checkpoint does not
    exist under its final name, so readers can never observe a partial
    write. Duck-compatible with the orbax async handle the previous API
    returned."""

    def __init__(self, ckptr, commit: _AtomicCommit, t0: Optional[float] = None):
        self._ckptr = ckptr
        self._commit = commit
        self._t0 = t0
        self.done = False
        self.path = commit.final

    @property
    def tmp_path(self) -> str:
        return self._commit.tmp

    def wait_until_finished(self):
        if self.done:
            return
        self._ckptr.wait_until_finished()
        self._commit.run()
        self.done = True
        self._ckptr.close()
        if self._t0 is not None:
            # async duration = save() call through commit: the window the
            # checkpoint was in flight, which is what overlap tuning needs
            _record_save(self.path, time.perf_counter() - self._t0)


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    async_save: bool = False, atomic: bool = True):
    """paddle.distributed.checkpoint.save_state_dict parity (Orbax-backed).

    Sharded arrays are written shard-by-shard per host; replicated arrays are
    written once. `async_save` returns a PendingSave immediately; the commit
    happens on its `wait_until_finished()`.

    With `atomic` (default) the body is written under `<path>.ptsave-tmp`
    and only renamed to `path` after a checksum manifest is in place, so a
    kill -9 at any point never leaves a torn directory under the final name
    (see docs/FAULT_TOLERANCE.md).
    """
    import orbax.checkpoint as ocp

    t0 = time.perf_counter()
    path = os.path.abspath(path)
    arrays = _to_arrays(state_dict)
    if not atomic:
        if async_save:
            # legacy raw-orbax handle: no commit hook to time against
            ckptr = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler(),
                multiprocessing_options=_mp_options())
            ckptr.save(path, args=ocp.args.StandardSave(arrays), force=True)
            return ckptr
        with _checkpointer() as ckptr:
            ckptr.save(path, arrays, force=True)
        _record_save(path, time.perf_counter() - t0)
        return None

    tmp = path + TMP_SUFFIX
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    commit = _AtomicCommit(tmp, path, meta=_layout_meta(arrays))
    if async_save:
        ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler(),
            multiprocessing_options=_mp_options())
        ckptr.save(tmp, args=ocp.args.StandardSave(arrays), force=True)
        return PendingSave(ckptr, commit, t0=t0)
    with _checkpointer() as ckptr:
        ckptr.save(tmp, arrays, force=True)
    commit.run()
    _record_save(path, time.perf_counter() - t0)
    return None


def _layout_meta(arrays: Dict[str, Any]) -> Optional[dict]:
    """Manifest meta carrying the source mesh + per-leaf PartitionSpec —
    what restore-anywhere plans against (see distributed/reshard.py)."""
    from ..reshard import LAYOUT_KEY, record_layouts

    rec = record_layouts(arrays)
    return {LAYOUT_KEY: rec} if rec else None


def is_complete_checkpoint(path: str) -> bool:
    """Cheap commit check: the manifest exists and every listed file is
    present with the recorded size. A dir failing this was torn mid-save
    and must never be restored."""
    return _manifest.is_complete(path)


def verify_checkpoint(path: str, deep: bool = True):
    """(ok, reason). `deep` re-checksums every file against the commit
    manifest — catches silent byte corruption, not just truncation."""
    return _manifest.verify(path, deep=deep)


def load_state_dict(
    path: str,
    state_dict: Optional[Dict[str, Any]] = None,
    process_group=None,
    coordinator_rank: int = 0,
):
    """Load, re-sharding onto the CURRENT placements.

    If `state_dict` is given (tensors with live shardings), each loaded array
    is materialized directly with the target's sharding — a checkpoint saved
    under dp8 loads onto mp4×dp2 without a conversion step — and the dict is
    updated in place (paddle parity). Otherwise returns plain arrays.
    """
    import orbax.checkpoint as ocp

    t0 = time.perf_counter()
    path = os.path.abspath(path)
    if state_dict is None:
        with _checkpointer() as ckptr:
            out = ckptr.restore(path)
        _record_restore(path, time.perf_counter() - t0)
        return out

    arrays = _to_arrays(state_dict)
    target = jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=getattr(v, "sharding", None))
        if hasattr(v, "shape")
        else v,
        arrays,
    )
    try:
        with _checkpointer() as ckptr:
            _check_saved_shapes(ckptr, path, target)
            restored = ckptr.restore(path, target)
    except (ValueError, TypeError, KeyError) as e:
        from ..reshard import legacy_error, read_layout_record

        if read_layout_record(path) is None:
            # pre-layout-record checkpoint failing to land on the live
            # placements: say so, instead of the deep jax/orbax mismatch
            raise legacy_error(path, e) from e
        raise
    for k, v in state_dict.items():
        if isinstance(v, Tensor) and k in restored:
            r = restored[k]
            if not isinstance(r, jax.Array):
                # multiprocess local state round-trips through numpy (see
                # _exportable); re-place it on the live tensor's devices
                r = jax.device_put(np.asarray(r),
                                   getattr(raw(v), "sharding", None))
            v._rebind(r)
    _record_restore(path, time.perf_counter() - t0)
    return state_dict


def _shape_mismatches(saved, target, prefix=""):
    out = []
    for k, v in saved.items():
        key = f"{prefix}{k}"
        t = target.get(k) if isinstance(target, dict) else None
        if t is None:
            continue
        if isinstance(v, dict) and isinstance(t, dict):
            out.extend(_shape_mismatches(v, t, key + "/"))
        else:
            ss = getattr(v, "shape", None)
            ts = getattr(t, "shape", None)
            if ss is not None and ts is not None and tuple(ss) != tuple(ts):
                out.append(f"{key}: saved {tuple(ss)} vs target {tuple(ts)}")
    return out


def _check_saved_shapes(ckptr, path: str, target) -> None:
    """Reject global-shape drift BEFORE orbax reads: tensorstore silently
    zero-fills the out-of-range region when the requested global shape
    exceeds the saved one (observed on this orbax), which corrupts a
    restore instead of failing it. Typical trigger: a legacy per-rank
    export (shard-local shapes) restored onto a full-shape target."""
    try:
        saved = ckptr.metadata(path)
    except Exception:
        return  # metadata unavailable: let restore surface its own error
    if not isinstance(saved, dict) or not isinstance(target, dict):
        return
    bad = _shape_mismatches(saved, target)
    if bad:
        raise ValueError(
            "checkpoint leaf shapes do not match the restore target: "
            + "; ".join(bad[:3])
            + (f" (+{len(bad) - 3} more)" if len(bad) > 3 else ""))


def _record_restore(path: str, seconds: float) -> None:
    _obs.observe("checkpoint_restore_seconds", seconds)
    _obs.event("checkpoint_restore", path=path, seconds=round(seconds, 6))
    _obs.record_span("ckpt_restore", dur_s=seconds, path=path)


save = save_state_dict
load = load_state_dict
