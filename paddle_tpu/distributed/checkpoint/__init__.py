"""Distributed checkpointing — sharded, async, topology-independent.

Reference capability (SURVEY.md §5 "Checkpoint/resume"): per-rank sharded
state dicts (GroupSharded), `fleet.save_persistables`, and the auto-parallel
**checkpoint converter** (`auto_parallel/static/converter.py`) that re-slices
checkpoints across different parallel configs.

TPU-native design: Orbax. Every host writes its local shards; metadata maps
global shape → shards; on load, passing the *target* shardings re-slices
automatically — the whole converter subsystem becomes a load argument
(SURVEY.md §7 "Hard parts": checkpoint re-sharding). Async save overlaps
serialization with training steps.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...framework.core import Tensor
from ...framework.op import raw


def _to_arrays(state_dict: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            out[k] = raw(v)
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        else:
            out[k] = v
    return out


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_state_dict(state_dict: Dict[str, Any], path: str, async_save: bool = False):
    """paddle.distributed.checkpoint.save_state_dict parity (Orbax-backed).

    Sharded arrays are written shard-by-shard per host; replicated arrays are
    written once. `async_save` returns immediately and flushes on the next
    save/wait (orbax async machinery).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    arrays = _to_arrays(state_dict)
    if async_save:
        ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        ckptr.save(path, args=ocp.args.StandardSave(arrays), force=True)
        return ckptr
    with _checkpointer() as ckptr:
        ckptr.save(path, arrays, force=True)
    return None


def load_state_dict(
    path: str,
    state_dict: Optional[Dict[str, Any]] = None,
    process_group=None,
    coordinator_rank: int = 0,
):
    """Load, re-sharding onto the CURRENT placements.

    If `state_dict` is given (tensors with live shardings), each loaded array
    is materialized directly with the target's sharding — a checkpoint saved
    under dp8 loads onto mp4×dp2 without a conversion step — and the dict is
    updated in place (paddle parity). Otherwise returns plain arrays.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if state_dict is None:
        with _checkpointer() as ckptr:
            return ckptr.restore(path)

    arrays = _to_arrays(state_dict)
    target = jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=getattr(v, "sharding", None))
        if hasattr(v, "shape")
        else v,
        arrays,
    )
    with _checkpointer() as ckptr:
        restored = ckptr.restore(path, target)
    for k, v in state_dict.items():
        if isinstance(v, Tensor) and k in restored:
            v._rebind(restored[k])
    return state_dict


save = save_state_dict
load = load_state_dict
