"""Commit manifest for crash-safe checkpoints.

A checkpoint directory is COMMITTED only once it contains a manifest listing
every file with its size and crc32. The writer produces the manifest after
the body write and renames the whole directory into place afterwards, so:

  * a directory without a manifest is torn (the writer died mid-save) and
    must never be restored;
  * a directory whose bytes no longer match the manifest (bit rot, partial
    overwrite, deliberate corruption) is detectable before restore.

Kept dependency-light (stdlib only): the fault-injection harness
(`paddle_tpu.testing.chaos`) and resume-path verification both import it
without dragging in jax/orbax.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Dict, Optional, Tuple

MANIFEST_NAME = "pt_manifest.json"
_CHUNK = 1 << 20


def manifest_path(root: str) -> str:
    return os.path.join(root, MANIFEST_NAME)


def fsync_dir(path: str) -> None:
    """Durably record a directory entry (the rename that commits a
    checkpoint is only crash-safe once its parent directory is synced)."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _iter_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            if dirpath == root and fn == MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, fn)
            yield os.path.relpath(full, root), full


def _crc32(path: str) -> int:
    h = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h = zlib.crc32(chunk, h)
    return h & 0xFFFFFFFF


def write_manifest(root: str, meta: Optional[dict] = None) -> dict:
    """Checksum every file under `root` and write the manifest atomically
    (tmp + rename + dir fsync). Call only after the body write finished —
    this is the commit record torn-write detection keys off."""
    files: Dict[str, dict] = {}
    for rel, full in _iter_files(root):
        files[rel] = {"size": os.path.getsize(full), "crc32": _crc32(full)}
    doc = {"format": 1, "files": files}
    if meta:
        doc["meta"] = meta
    tmp = manifest_path(root) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, manifest_path(root))
    fsync_dir(root)
    return doc


def read_manifest(root: str) -> Optional[dict]:
    try:
        with open(manifest_path(root)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc.get("files"), dict) else None


def is_complete(root: str) -> bool:
    """Cheap commit check: manifest present/parses and every listed file
    exists with the recorded size (no checksumming)."""
    return verify(root, deep=False)[0]


def verify(root: str, deep: bool = True) -> Tuple[bool, str]:
    """(ok, reason). `deep` re-checksums every file; shallow checks
    existence + size only."""
    if not os.path.isdir(root):
        return False, "not a directory"
    doc = read_manifest(root)
    if doc is None:
        return False, "no commit manifest (torn/incomplete write)"
    for rel, ent in sorted(doc["files"].items()):
        full = os.path.join(root, rel)
        if not os.path.isfile(full):
            return False, f"missing file {rel!r}"
        size = os.path.getsize(full)
        if size != ent.get("size"):
            return False, f"size mismatch for {rel!r}: {size} != {ent.get('size')}"
        if deep and _crc32(full) != ent.get("crc32"):
            return False, f"checksum mismatch for {rel!r}"
    return True, "ok"
