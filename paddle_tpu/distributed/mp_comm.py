"""Quantized tensor-parallel (activation) collectives — the mp/sharding
analogue of the dp gradient wire in ``grad_comm``.

Motivation (ROADMAP open item 1): ``comm_analysis`` shows the bytes are
NOT on the dp gradient exchange — mp-involving axes dominate ``per_axis``
traffic in MULTICHIP_SCALING.json. EQuARX (arXiv 2506.17615) demonstrates
that quantized all-reduce/all-gather with full-precision accumulation
preserves quality at large wire savings; Mesh-TensorFlow
(arXiv 1811.02084) is the canonical statement of why mp activation
collectives sit on the critical path of every layer.

Design — GSPMD has no "quantize this collective" hook, so the mp
all-reduce a Row-parallel matmul implies cannot be re-dtyped in place.
Instead each wire site restructures the contraction with an EXPLICIT
block dim of extent G = mp degree carrying per-shard f32 partial sums,
constrained sharded over ``mp`` (shard-local by construction):

    quantize per (row, block) absmax  →  int8 payload + f32 scales
    sharding-constraint to replicated →  XLA emits an s8 all-gather
    dequantize, sum the block dim     →  exact f32 accumulation

The recombination is associatively identical to GSPMD's per-shard-partial
+ all-reduce, but the bytes that cross the mesh are the wire dtype's —
an HLO-measurable drop, not a simulation (``comm_analysis`` prices the
s8/bf16 operands directly). The backward is a straight-through
``custom_vjp`` whose cotangent is wire-round-tripped symmetrically
(the ``grad_comm.wire_cast`` idiom).

Inside fully-manual shard_map regions (the explicit dp step, pipeline
regions) the same wire rides ``collective.all_gather_quantized`` — a real
reduced-precision ``lax.all_gather`` with per-leaf absmax scales.

Config: ``DistributedStrategy.mp_comm`` / ``mp_comm_configs``, overridden
by ``PADDLE_TPU_MP_COMM`` — the SAME ``off/on/f32/bf16/int8`` + ``k=v``
grammar as ``PADDLE_TPU_GRAD_COMM`` (one parser,
``grad_comm.parse_wire_env``, two prefixes). ``mp_comm_*`` metrics are
recorded ONLY from this module (``scripts/check_observability.py``).

See docs/GRAD_COMM.md ("activation wire") and docs/SERVING.md §5 (the
decode logit recombination + exact-argmax verify rule).
"""
from __future__ import annotations

import contextlib
import functools
import threading
from dataclasses import dataclass, replace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import observability as _obs
from . import mesh as _mesh
from .grad_comm import (WIRE_DTYPES, _bool_key, parse_wire_env,
                        quantize_absmax, quantize_roundtrip)


@dataclass(frozen=True)
class MpCommConfig:
    """Resolved activation-wire knobs (docs/GRAD_COMM.md "activation
    wire"). ``enable`` + a sub-f32 ``wire_dtype`` turn the blocked
    quantized recombination on; everything else is refinement."""

    enable: bool = False
    wire_dtype: str = "f32"
    # accepted for grammar parity with PADDLE_TPU_GRAD_COMM; activation
    # collectives are stateless (a fresh tensor every step), so there is
    # no residual to carry — documented honestly in docs/GRAD_COMM.md
    error_feedback: bool = False
    # quantize the ZeRO-3 parameter all-gathers inside manual regions
    # (floored at bf16: int8 weights without error feedback would bias
    # the model every step)
    zero_gather: bool = True
    # decode logit recombination: exchange per-shard (max, argmax)
    # exactly alongside the quantized payload so greedy argmax is
    # bit-equal to the unsharded engine (docs/SERVING.md §5)
    logit_verify: bool = True

    @property
    def quantized(self) -> bool:
        return self.enable and self.wire_dtype in ("bf16", "int8")

    @property
    def act_wire(self) -> Optional[str]:
        return self.wire_dtype if self.quantized else None

    @property
    def param_gather_wire(self) -> Optional[str]:
        if not (self.quantized and self.zero_gather):
            return None
        return "bf16"

    @property
    def wire_itemsize(self) -> int:
        return {"f32": 4, "bf16": 2, "int8": 1}[self.wire_dtype]


_TLS = threading.local()


@contextlib.contextmanager
def activation_wire_disabled():
    """Force the in-model activation wire OFF for anything traced inside.

    The decode engine wraps its program traces with this: model-internal
    mp collectives must stay exact so the greedy bit-equality contract
    holds — serving quantizes ONLY the logit recombination, whose argmax
    is restored exactly by the verify exchange."""
    prev = getattr(_TLS, "disabled", False)
    _TLS.disabled = True
    try:
        yield
    finally:
        _TLS.disabled = prev


def _strategy_config(strategy) -> MpCommConfig:
    cfg = MpCommConfig()
    if strategy is None:
        return cfg
    enable = bool(getattr(strategy, "mp_comm", False))
    sub = dict(getattr(strategy, "mp_comm_configs", {}) or {})
    wire = str(sub.get("wire_dtype", cfg.wire_dtype)).lower()
    if wire not in WIRE_DTYPES:
        raise ValueError(
            f"mp_comm_configs.wire_dtype={wire!r} not in {WIRE_DTYPES}")
    return replace(
        cfg,
        enable=enable,
        wire_dtype=wire,
        error_feedback=bool(sub.get("error_feedback", cfg.error_feedback)),
        zero_gather=bool(sub.get("zero_gather", cfg.zero_gather)),
        logit_verify=bool(sub.get("logit_verify", cfg.logit_verify)),
    )


def resolve_config(strategy=None) -> MpCommConfig:
    """Strategy knobs overridden by ``PADDLE_TPU_MP_COMM`` — the same
    grammar as ``PADDLE_TPU_GRAD_COMM`` (``grad_comm.parse_wire_env``):
    bare modes ``off``/``on``/``f32``/``bf16``/``int8`` plus ``k=v`` keys
    ``wire``, ``enable``, ``ef``/``error_feedback``, ``zero_gather``,
    ``verify``/``logit_verify``."""
    if getattr(_TLS, "disabled", False):
        return MpCommConfig()
    if strategy is None:
        from . import fleet as _fleet

        strategy = _fleet.fleet_strategy()
    cfg = _strategy_config(strategy)
    var = "PADDLE_TPU_MP_COMM"
    return parse_wire_env(var, cfg, {
        "ef": _bool_key(var, "error_feedback"),
        "error_feedback": _bool_key(var, "error_feedback"),
        "zero_gather": _bool_key(var, "zero_gather"),
        "verify": _bool_key(var, "logit_verify"),
        "logit_verify": _bool_key(var, "logit_verify"),
    })


# ----------------------------------------------------------- telemetry ----
# trace-time accumulators behind mp_comm_quantized_fraction: analytic wire
# vs f32 bytes across every blocked site built so far (static shapes only,
# never tracers)
_totals = {"f32": 0.0, "wire": 0.0}


def _record_site(out_elems: int, g: int, wire_dtype: str,
                 scale_elems: int) -> None:
    it = {"bf16": 2, "int8": 1}.get(wire_dtype)
    if it is None:
        return
    # baseline: ring all-reduce of the f32 output; wire: all-gather of the
    # per-shard int8/bf16 partials + f32 scales
    f32_b = 2.0 * (g - 1) / g * 4.0 * out_elems
    wire_b = float(g - 1) * out_elems * it + (g - 1) / g * scale_elems * 4.0
    _totals["f32"] += f32_b
    _totals["wire"] += wire_b
    _obs.inc("mp_comm_sites_total")
    _obs.inc("mp_comm_wire_bytes_total", wire_b)
    if _totals["f32"] > 0:
        _obs.set_gauge("mp_comm_quantized_fraction",
                       1.0 - _totals["wire"] / _totals["f32"])


# ------------------------------------------- blocked GSPMD recombination ----
def _blocked_recombine(z, wire_dtype: str, spec: P):
    """Forward math of :func:`blocked_psum` (no vjp attached).

    ``z [..., G, K]`` carries per-mp-shard f32 partial sums on the -2
    block dim; ``spec`` is z's layout with ``"mp"`` at that dim (shard
    j holds block j — no data movement to set up). The payload crosses
    the mesh at ``wire_dtype`` (int8 with per-(row, block) absmax scales,
    or bf16) and the block sum runs in f32 after dequantization."""
    m = _mesh.get_global_mesh()
    z = z.astype(jnp.float32)
    nd = z.ndim
    entries = list(spec) + [None] * (nd - len(spec))
    bspec = P(*entries)
    rep = P(*[None if i == nd - 2 else entries[i] for i in range(nd)])
    z = _mesh.sharding_constraint(z, bspec, m)
    if wire_dtype == "bf16":
        # the payload crosses as a u16 BITCAST of the bf16 value: float
        # normalization (and the algebraic simplifier) otherwise legalize
        # a bf16 all-gather back to convert∘f32-gather∘convert and the
        # wire silently moves f32 bytes again
        u = jax.lax.bitcast_convert_type(z.astype(jnp.bfloat16), jnp.uint16)
        u = _mesh.sharding_constraint(u, bspec, m)
        zr = jax.lax.bitcast_convert_type(
            _mesh.sharding_constraint(u, rep, m),
            jnp.bfloat16).astype(jnp.float32)
        scale_elems = 0
    elif wire_dtype == "int8":
        q, scale = quantize_absmax(z, axis=-1)
        q = _mesh.sharding_constraint(q, bspec, m)
        scale = _mesh.sharding_constraint(scale, bspec, m)
        zr = (_mesh.sharding_constraint(q, rep, m).astype(jnp.float32)
              * _mesh.sharding_constraint(scale, rep, m))
        scale_elems = int(np.prod(scale.shape))
    else:
        zr = z
        scale_elems = 0
    out = jnp.sum(zr, axis=-2)
    if wire_dtype in ("bf16", "int8"):
        _record_site(int(np.prod(out.shape)), int(z.shape[-2]), wire_dtype,
                     scale_elems)
    out_spec = P(*[entries[i] for i in range(nd) if i != nd - 2])
    return _mesh.sharding_constraint(out, out_spec, m)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def blocked_psum(z, wire_dtype: str, spec: P):
    """Sum per-mp-shard partials carried on the -2 block dim of ``z``
    through a reduced-precision wire with f32 accumulation. Numerically
    the psum_quantized contract; physically a real int8/bf16 payload in
    the compiled HLO. The backward is straight-through with the cotangent
    wire-round-tripped symmetrically."""
    return _blocked_recombine(z, wire_dtype, spec)


def _blocked_psum_fwd(z, wire_dtype, spec):
    return _blocked_recombine(z, wire_dtype, spec), z.shape[-2]


def _blocked_psum_bwd(wire_dtype, spec, g, ct):
    ct = quantize_roundtrip(ct.astype(jnp.float32), wire_dtype)
    dz = jnp.broadcast_to(ct[..., None, :],
                          ct.shape[:-1] + (g, ct.shape[-1]))
    return (_mesh.sharding_constraint(dz, spec, _mesh.get_global_mesh()),)


blocked_psum.defvjp(_blocked_psum_fwd, _blocked_psum_bwd)


# ------------------------------------------------- mp layer contractions ----
def _block_spec(nd: int, data_spec) -> P:
    entries = [None] * nd
    entries[0] = data_spec
    entries[nd - 2] = "mp"
    return P(*entries)


def row_parallel_matmul(x, w, g: int, wire_dtype: str, data_spec=None):
    """The RowParallelLinear contraction (``x [..., I]`` with I
    mp-sharded, ``w [I, O]`` sharded on dim 0) restructured with an
    explicit block dim so the per-shard partials recombine through
    :func:`blocked_psum` instead of GSPMD's implicit f32 all-reduce."""
    i, o = w.shape
    xb = x.reshape(x.shape[:-1] + (g, i // g))
    wb = w.reshape((g, i // g, o))
    z = jnp.einsum("...gi,gio->...go", xb, wb)
    return blocked_psum(z, wire_dtype, _block_spec(z.ndim, data_spec))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def column_parallel_linear(x, w, g: int, wire_dtype: str, data_spec=None):
    """``y = x @ w`` with w mp-sharded on the OUTPUT dim: the forward is
    collective-free (y stays mp-sharded); the backward dx — the one mp
    collective of a column-parallel layer — recombines through the
    blocked quantized wire, symmetric with the row-parallel forward."""
    return jnp.einsum("...i,io->...o", x, w)


def _col_fwd(x, w, g, wire_dtype, data_spec):
    return column_parallel_linear(x, w, g, wire_dtype, data_spec), (x, w)


def _col_bwd(g, wire_dtype, data_spec, res, ct):
    x, w = res
    ct32 = ct.astype(jnp.float32)
    dw = jnp.einsum("...i,...o->io", x.astype(jnp.float32), ct32)
    i, o = w.shape
    ctb = ct32.reshape(ct.shape[:-1] + (g, o // g))
    wb = w.astype(jnp.float32).reshape((i, g, o // g))
    z = jnp.einsum("...go,igo->...gi", ctb, wb)
    dx = _blocked_recombine(z, wire_dtype, _block_spec(z.ndim, data_spec))
    return dx.astype(x.dtype), dw.astype(w.dtype)


column_parallel_linear.defvjp(_col_fwd, _col_bwd)


def vocab_parallel_embedding(w, ids, g: int, wire_dtype: str, data_spec=None):
    """Embedding lookup on an mp-vocab-sharded table ``w [V, H]`` with
    the recombination all-reduce taken through the quantized blocked
    wire. Uses the one-hot-matmul formulation (the TPU-native lowering
    of a sharded-table gather) so each shard's masked partial is a plain
    batched contraction the partitioner keeps shard-local."""
    v, h = w.shape
    vg = v // g
    wb = w.reshape((g, vg, h))
    rel = ids[..., None].astype(jnp.int32) - (
        jnp.arange(g, dtype=jnp.int32) * vg)
    inb = (rel >= 0) & (rel < vg)
    oh = jax.nn.one_hot(jnp.clip(rel, 0, vg - 1), vg, dtype=w.dtype)
    oh = oh * inb[..., None].astype(w.dtype)
    z = jnp.einsum("...gv,gvh->...gh", oh, wb)
    return blocked_psum(z, wire_dtype, _block_spec(z.ndim, data_spec))


# ------------------------------------------- decode logit recombination ----
def quantized_logit_gather(logits, wire_dtype: str, mesh=None):
    """Replicate mp-vocab-sharded ``logits [..., V]`` with a
    reduced-precision payload plus an EXACT per-shard (max, argmax) side
    channel.

    Returns ``(wire_logits, exact_argmax)``: ``wire_logits`` is the
    replicated f32 dequantized payload (what sampled rows consume);
    ``exact_argmax`` reproduces ``jnp.argmax`` over the EXACT logits —
    per-block maxima and first-occurrence argmaxes are computed in f32
    BEFORE quantization and exchanged exactly (a V/vocab-sized fraction
    of the payload), then combined with first-occurrence tie-breaking
    across blocks. Greedy decode therefore stays bit-equal to the
    unsharded engine by construction (docs/SERVING.md §5).

    Returns None when the layout can't take the quantized path (no mp
    axis, vocab not divisible by the mp degree, or an f32 wire) — the
    caller falls back to the exact all-gather."""
    m = mesh or _mesh.get_global_mesh()
    if m is None or getattr(m, "empty", False):
        return None
    g = _mesh.mesh_axis_size("mp", m)
    v = logits.shape[-1]
    if g <= 1 or v % g != 0 or wire_dtype not in ("bf16", "int8"):
        return None
    lead = logits.shape[:-1]
    vg = v // g
    bspec = P(*([None] * len(lead) + ["mp"]))
    rep = P()
    lb = _mesh.sharding_constraint(
        logits.astype(jnp.float32).reshape(lead + (g, vg)), bspec, m)
    # exact per-block winners BEFORE quantization (tiny f32/i32 payload)
    bmax = _mesh.sharding_constraint(jnp.max(lb, axis=-1), bspec, m)
    barg = _mesh.sharding_constraint(
        jnp.argmax(lb, axis=-1).astype(jnp.int32), bspec, m)
    bmax_r = _mesh.sharding_constraint(bmax, rep, m)
    barg_r = _mesh.sharding_constraint(barg, rep, m)
    # blocks are vocab-ordered, jnp.argmax picks the FIRST max block and
    # the per-block argmax the first in-block index — together exactly
    # jnp.argmax's first-occurrence rule on the exact logits
    win = jnp.argmax(bmax_r, axis=-1)
    exact = (win.astype(jnp.int32) * vg + jnp.take_along_axis(
        barg_r, win[..., None], axis=-1)[..., 0]).astype(jnp.int32)
    if wire_dtype == "bf16":
        # see _blocked_recombine: the bf16 payload rides as a u16 bitcast
        u = jax.lax.bitcast_convert_type(lb.astype(jnp.bfloat16), jnp.uint16)
        u = _mesh.sharding_constraint(u, bspec, m)
        wl = jax.lax.bitcast_convert_type(
            _mesh.sharding_constraint(u, rep, m),
            jnp.bfloat16).astype(jnp.float32)
    else:
        q, scale = quantize_absmax(lb, axis=-1)
        q = _mesh.sharding_constraint(q, bspec, m)
        scale = _mesh.sharding_constraint(scale, bspec, m)
        wl = (_mesh.sharding_constraint(q, rep, m).astype(jnp.float32)
              * _mesh.sharding_constraint(scale, rep, m))
    return wl.reshape(lead + (v,)), exact


def logit_wire_bytes(rows: int, vocab: int, g: int,
                     wire_dtype: str) -> Tuple[float, float]:
    """Analytic per-call wire payload of the logit recombination:
    ``(f32_baseline_bytes, wire_bytes)`` for ``rows`` logit rows. The
    wire side counts the quantized payload, the f32 scales (int8 only)
    and the exact (max, argmax) verify exchange."""
    it = {"f32": 4, "bf16": 2, "int8": 1}[wire_dtype]
    frac = (g - 1) / g
    base = frac * rows * vocab * 4.0
    if wire_dtype == "f32":
        return base, base
    wire = frac * rows * vocab * it + frac * rows * g * 8.0
    if wire_dtype == "int8":
        wire += frac * rows * g * 4.0
    return base, wire
