"""Gradient-communication optimization layer: bucketing, wire quantization,
and ZeRO weight-update sharding primitives.

Reference capability (SURVEY.md §2.2 "Data parallel"): the reference fuses
per-parameter NCCL allreduces into size-targeted coalesced buffers
(`fused_allreduce_gradients`, `comm_buffer_size_MB`) so gradient exchange
overlaps with backward compute, and GroupSharded decomposes the weight
update into reduce-scatter(grad) → rank-local update → all-gather(param)
(`group_sharded_stage{2,3}.py`). DGC-style compressed exchange is the
closest reference analogue of the wire-quantized collectives here.

TPU-native design: there is no eager NCCL loop to fuse — every collective
is compiled into the step program. On this jax the building block is the
*fully-manual* `shard_map` region (`_jax_compat.shard_map`), whose boundary
autodiff gives exactly the mechanics we need (all verified empirically on
the CPU mesh backend):

* a replicated region input receives ONE boundary `psum` over the
  unmentioned mesh axes for its cotangent — so CONCATENATING N parameter
  leaves into one flat fusion buffer merges N per-tensor all-reduces into
  one per-bucket all-reduce, and splitting the gradient exchange into
  several buckets lets the XLA scheduler start early buckets' collectives
  while the backward of earlier layers is still running;
* an input entering SHARDED (its in_spec names the `sharding` axis) that is
  `all_gather`-ed inside the region transposes to `reduce_scatter` — the
  gradient leaves the region sharded, the optimizer update runs on the
  shard, and only the updated parameter is all-gathered: the
  "Automatic Cross-Replica Sharding of Weight Update" decomposition
  (arxiv 2004.13336), which also keeps ZeRO-3 parameter shards sharded
  *inside* pipeline regions;
* a `custom_vjp` identity whose backward round-trips the cotangent through
  the wire dtype implements precision-reduced collectives (bf16; int8 with
  per-bucket scales + error-feedback residuals, cf. EQuARX,
  arxiv 2506.17615) while accumulation stays f32-safe
  (`collective.psum_f32safe` semantics).

Config: `DistributedStrategy.grad_comm` / `grad_comm_configs`, overridden
by the `PADDLE_TPU_GRAD_COMM` env var (see `resolve_config`). Wire/payload
visibility: `comm_analysis.bucket_traffic` + the `grad_comm_*` metrics
registered in `observability/catalog.py` (recorded ONLY from this module —
`scripts/check_observability.py` enforces that ownership).
"""
from __future__ import annotations

import functools
import os
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import observability as _obs
from .collective import psum_f32safe as _psum_f32safe

WIRE_DTYPES = ("f32", "bf16", "int8")

# int8 symmetric range: per-bucket absmax scale maps onto [-127, 127]
_INT8_LEVELS = 127.0


@dataclass(frozen=True)
class GradCommConfig:
    """Resolved gradient-communication knobs.

    `enable` gates the *optimization* features (bucketed fusion buffers,
    wire quantization, the explicit data-parallel step). `zero_update` and
    `pipeline_batch_shard` default on independently: the first is the
    ZeRO weight-update decomposition (a memory/traffic correctness fix for
    sharded state inside pipeline regions), the second reverses the
    batch-compute replication of the fully-manual pipeline region — both
    are numerics-preserving and carry their own kill switches.
    """

    enable: bool = False
    bucket_mb: float = 32.0
    wire_dtype: str = "f32"
    error_feedback: bool = False
    zero_update: bool = True
    pipeline_batch_shard: bool = True
    # issue each tail bucket's collective INSIDE the backward chain, as its
    # cotangents finalize, instead of after the full backward (docs/
    # PIPELINE.md §4). ZeRO buckets (shard-shaped scatter result) and
    # error-feedback (residual state can't escape a vjp) keep the
    # post-backward issue regardless.
    overlap: bool = True

    @property
    def quantized(self) -> bool:
        return self.wire_dtype != "f32"

    @property
    def bucket_bytes(self) -> int:
        return max(int(self.bucket_mb * (1 << 20)), 1)

    @property
    def wire_itemsize(self) -> int:
        return {"f32": 4, "bf16": 2, "int8": 1}[self.wire_dtype]


_TRUE = {"1", "on", "true", "yes"}
_FALSE = {"0", "off", "false", "no"}


def _strategy_config(strategy) -> GradCommConfig:
    cfg = GradCommConfig()
    if strategy is None:
        return cfg
    enable = bool(getattr(strategy, "grad_comm", False))
    sub = dict(getattr(strategy, "grad_comm_configs", {}) or {})
    wire = str(sub.get("wire_dtype", cfg.wire_dtype)).lower()
    if wire not in WIRE_DTYPES:
        raise ValueError(
            f"grad_comm_configs.wire_dtype={wire!r} not in {WIRE_DTYPES}")
    # the reference's comm_buffer_size_MB lives on DistributedStrategy as
    # fuse_grad_size_in_MB — honor it as the bucket-size default
    default_mb = float(getattr(strategy, "fuse_grad_size_in_MB", cfg.bucket_mb)
                       or cfg.bucket_mb)
    return replace(
        cfg,
        enable=enable,
        bucket_mb=float(sub.get("bucket_mb", default_mb)),
        wire_dtype=wire,
        error_feedback=bool(sub.get("error_feedback", cfg.error_feedback)),
        zero_update=bool(sub.get("zero_update", cfg.zero_update)),
        pipeline_batch_shard=bool(
            sub.get("pipeline_batch_shard", cfg.pipeline_batch_shard)),
        overlap=bool(sub.get("overlap", cfg.overlap)),
    )


def _parse_bool(env_var: str, key: str, v: str) -> bool:
    """Strict boolean values: anything outside the on/off vocabulary is a
    hard error — ``ef=maybe`` must never silently parse as False."""
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    raise ValueError(
        f"{env_var}: {key}={v!r} is not a boolean "
        f"(want one of {tuple(sorted(_TRUE | _FALSE))})")


def _bool_key(env_var: str, field: str):
    def apply(cfg, v):
        return replace(cfg, **{field: _parse_bool(env_var, field, v)})
    return apply


def parse_wire_env(env_var: str, cfg, extra_keys=None):
    """The shared ``off/on/f32/bf16/int8`` + ``k=v`` comm-wire env grammar
    — ONE implementation behind both prefixes (``PADDLE_TPU_GRAD_COMM``
    here, ``PADDLE_TPU_MP_COMM`` in ``mp_comm``).

    ``cfg`` is any frozen dataclass with ``enable`` and ``wire_dtype``
    fields; ``extra_keys`` maps prefix-specific key names to
    ``f(cfg, value) -> cfg`` appliers. Unknown bare tokens, unknown keys,
    and non-boolean values for boolean keys are all hard errors — a typo
    must never silently run the f32 wire."""
    raw = os.environ.get(env_var, "").strip().lower()
    if not raw:
        return cfg
    extra_keys = extra_keys or {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            # bare mode tokens compose with k=v ones: "on,bucket_mb=8"
            if part in _FALSE:
                cfg = replace(cfg, enable=False)
            elif part in _TRUE or part == "f32":
                cfg = replace(cfg, enable=True, wire_dtype="f32")
            elif part in ("bf16", "int8"):
                cfg = replace(cfg, enable=True, wire_dtype=part)
            else:
                raise ValueError(
                    f"{env_var}: bad token {part!r} (want k=v, or "
                    f"a mode from {('off', 'on', 'f32', 'bf16', 'int8')})")
            continue
        k, v = (s.strip() for s in part.split("=", 1))
        if k in ("wire", "wire_dtype"):
            if v not in WIRE_DTYPES:
                raise ValueError(
                    f"{env_var} wire={v!r} not in {WIRE_DTYPES}")
            cfg = replace(cfg, wire_dtype=v, enable=True)
        elif k == "enable":
            cfg = replace(cfg, enable=_parse_bool(env_var, k, v))
        elif k in extra_keys:
            cfg = extra_keys[k](cfg, v)
        else:
            raise ValueError(f"{env_var}: unknown key {k!r}")
    return cfg


def resolve_config(strategy=None) -> GradCommConfig:
    """Strategy knobs overridden by ``PADDLE_TPU_GRAD_COMM``.

    Env grammar (case-insensitive, shared with ``PADDLE_TPU_MP_COMM`` —
    see :func:`parse_wire_env`):
      ``off``/``0``            disable bucketing/quantization (the
                               zero_update / batch-shard fixes keep their
                               defaults; use explicit keys to kill them)
      ``on``/``1``/``f32``     enable with f32 wire
      ``bf16`` / ``int8``      enable with that wire dtype
      comma list of ``k=v``    fine-grained: ``wire=int8,bucket_mb=8,``
                               ``error_feedback=1,zero=0,batch_shard=0,``
                               ``overlap=0,enable=1``
    """
    if strategy is None:
        from . import fleet as _fleet

        strategy = _fleet.fleet_strategy()
    cfg = _strategy_config(strategy)
    var = "PADDLE_TPU_GRAD_COMM"
    return parse_wire_env(var, cfg, {
        "bucket_mb": lambda c, v: replace(c, bucket_mb=float(v), enable=True),
        "ef": _bool_key(var, "error_feedback"),
        "error_feedback": _bool_key(var, "error_feedback"),
        "zero": _bool_key(var, "zero_update"),
        "zero_update": _bool_key(var, "zero_update"),
        "batch_shard": _bool_key(var, "pipeline_batch_shard"),
        "pipeline_batch_shard": _bool_key(var, "pipeline_batch_shard"),
        "overlap": _bool_key(var, "overlap"),
    })


# --------------------------------------------------------------- bucketing --
def build_buckets(sizes_bytes: Sequence[int], target_bytes: int) -> List[List[int]]:
    """Greedy, order-preserving grouping of tensor indices into buckets of
    ~``target_bytes``. Order preservation matters: backward visits
    parameters roughly last-to-first, so keeping construction order keeps
    each bucket's members adjacent in the backward schedule — the property
    that lets its collective start while earlier layers still compute."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, sz in enumerate(sizes_bytes):
        if cur and cur_bytes + int(sz) > target_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += int(sz)
    if cur:
        buckets.append(cur)
    return buckets


@dataclass(frozen=True)
class BucketLayout:
    """Static layout of one flat fusion buffer: which leaves, where."""

    indices: Tuple[int, ...]          # leaf indices (into the caller's list)
    shapes: Tuple[Tuple[int, ...], ...]
    offsets: Tuple[int, ...]          # flat element offsets
    sizes: Tuple[int, ...]            # flat element counts
    total: int                        # bucket length in elements


def make_layouts(shapes: Sequence[Tuple[int, ...]], itemsizes: Sequence[int],
                 target_bytes: int, *, lead_dims: int = 0,
                 indices: Optional[Sequence[int]] = None) -> List[BucketLayout]:
    """Bucket a list of tensors into flat-buffer layouts. With ``lead_dims``
    the leading dims are preserved by pack/unpack and offsets/sizes count
    elements PER lead-slice (grouping still targets full-tensor bytes).
    ``indices`` relabels position j in ``shapes`` to a caller index."""
    full = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = [int(np.prod(s[lead_dims:])) if s[lead_dims:] else 1 for s in shapes]
    groups = build_buckets(
        [n * it for n, it in zip(full, itemsizes)], target_bytes)
    out = []
    for g in groups:
        offs, off = [], 0
        for j in g:
            offs.append(off)
            off += flat[j]
        out.append(BucketLayout(
            indices=tuple(indices[j] if indices is not None else j for j in g),
            shapes=tuple(tuple(shapes[j]) for j in g),
            offsets=tuple(offs),
            sizes=tuple(flat[j] for j in g),
            total=off,
        ))
    return out


def pack_bucket(leaves, layout: BucketLayout, *, lead_dims: int = 0):
    """Concatenate ``leaves[i]`` for i in the layout into one flat buffer.
    ``lead_dims`` leading dims (e.g. the stacked layer dim of a pipeline
    leaf) are preserved; the rest flattens."""
    parts = []
    for i in layout.indices:
        v = leaves[i]
        lead = v.shape[:lead_dims]
        parts.append(v.reshape(lead + (-1,)))
    return jnp.concatenate(parts, axis=lead_dims)


def unpack_bucket(bucket, layout: BucketLayout, *, lead_dims: int = 0):
    """Inverse of :func:`pack_bucket`: list of (index, leaf) pairs."""
    out = []
    lead = bucket.shape[:lead_dims]
    for i, off, n, shape in zip(
            layout.indices, layout.offsets, layout.sizes, layout.shapes):
        sl = lax.slice_in_dim(bucket, off, off + n, axis=lead_dims)
        out.append((i, sl.reshape(lead + tuple(shape[lead_dims:]))))
    return out


# ------------------------------------------------------- wire quantization --
def quantize_absmax(v, axis=None):
    """Symmetric int8 quantization with an absmax scale over ``axis``
    (``None`` = one scale for the whole array, the gradient-wire shape;
    the serving KV cache passes the head_dim axis for per-head scales).
    Returns ``(q_int8, scale_f32)`` with ``scale`` keeping reduced dims."""
    scale = jnp.maximum(
        jnp.max(jnp.abs(v.astype(jnp.float32)), axis=axis, keepdims=True)
        / _INT8_LEVELS,
        jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(jnp.round(v.astype(jnp.float32) / scale),
                 -_INT8_LEVELS, _INT8_LEVELS)
    return q.astype(jnp.int8), scale


def dequantize_absmax(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_absmax` (scale broadcasts over the
    reduced axes it kept)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def quantize_roundtrip(v, wire_dtype: str):
    """Project ``v`` onto what the wire dtype can represent (f32-safe
    accumulation semantics: the payload is quantized once, the reduction
    itself accumulates in f32 via psum_f32safe — see docs/GRAD_COMM.md for
    why this is numerics-faithful to a native low-precision collective)."""
    if wire_dtype == "bf16":
        return v.astype(jnp.bfloat16).astype(v.dtype)
    if wire_dtype == "int8":
        q, scale = quantize_absmax(v)
        return dequantize_absmax(q, scale, v.dtype)
    return v


def quantize_with_feedback(v, residual, wire_dtype: str):
    """Error-feedback compression: send quant(v + residual), carry the
    quantization error to the next step (residual lives in optimizer
    state; see HybridParallelOptimizer)."""
    c = v + residual.astype(v.dtype)
    q = quantize_roundtrip(c, wire_dtype)
    return q, (c - q).astype(residual.dtype)


def wire_cast(v, wire_dtype: str):
    """Identity whose COTANGENT is round-tripped through the wire dtype.

    Placed on a fusion buffer just inside a shard_map region, the boundary
    psum of that buffer's cotangent carries exactly the quantized payload —
    the trick that wire-compresses a collective jax itself inserts."""
    if wire_dtype == "f32":
        return v
    return _wire_cast_vjp(v, wire_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _wire_cast_vjp(v, wire_dtype):
    return v


def _wire_cast_fwd(v, wire_dtype):
    return v, None


def _wire_cast_bwd(wire_dtype, _res, ct):
    return (quantize_roundtrip(ct, wire_dtype),)


_wire_cast_vjp.defvjp(_wire_cast_fwd, _wire_cast_bwd)


# ------------------------------------------------- sharded (ZeRO) layouts --
@dataclass(frozen=True)
class ShardLayout:
    """Shard-major flat layout for psum_scatter / all_gather round trips.

    Leaves are split into ``nshards`` static slices along ``dims[i]``; the
    flat buffer concatenates [shard 0 of every leaf, shard 1 of every
    leaf, ...] so a tiled dim-0 ``psum_scatter`` hands rank s exactly its
    contiguous shard block, and a tiled ``all_gather`` of updated shard
    blocks reassembles in the same order."""

    indices: Tuple[int, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    dims: Tuple[int, ...]             # shard dim per leaf
    nshards: int
    shard_sizes: Tuple[int, ...]      # per-leaf elements in ONE shard slice
    block: int                        # elements per shard block

    @property
    def total(self) -> int:
        return self.block * self.nshards


def make_shard_layout(indices: Sequence[int],
                      shapes: Sequence[Tuple[int, ...]],
                      dims: Sequence[int], nshards: int) -> ShardLayout:
    shard_sizes = []
    for shape, d in zip(shapes, dims):
        if shape[d] % nshards != 0:
            raise ValueError(
                f"shape {shape} dim {d} not divisible by {nshards} shards")
        shard_sizes.append(int(np.prod(shape)) // nshards)
    return ShardLayout(
        indices=tuple(indices),
        shapes=tuple(tuple(s) for s in shapes),
        dims=tuple(int(d) for d in dims),
        nshards=int(nshards),
        shard_sizes=tuple(shard_sizes),
        block=int(sum(shard_sizes)),
    )


def pack_shard_major(leaves, layout: ShardLayout):
    """Full leaves -> one flat shard-major buffer (layout.total elements)."""
    split = [jnp.split(leaves[i], layout.nshards, axis=d)
             for i, d in zip(layout.indices, layout.dims)]
    blocks = []
    for s in range(layout.nshards):
        blocks.extend(parts[s].reshape(-1) for parts in split)
    return jnp.concatenate(blocks)


def unpack_shard_block(block, layout: ShardLayout):
    """One rank's shard block -> list of (index, shard-slice) pairs, each
    shaped like the leaf with ``dims[i]`` divided by nshards."""
    out, off = [], 0
    for i, shape, d, n in zip(layout.indices, layout.shapes, layout.dims,
                              layout.shard_sizes):
        sshape = list(shape)
        sshape[d] //= layout.nshards
        out.append((i, lax.slice_in_dim(block, off, off + n).reshape(sshape)))
        off += n
    return out


def unpack_gathered(flat, layout: ShardLayout):
    """Tiled all_gather output (shard-major, layout.total elements) -> list
    of (index, full leaf) pairs."""
    blocks = [lax.slice_in_dim(flat, s * layout.block, (s + 1) * layout.block)
              for s in range(layout.nshards)]
    per_shard = [unpack_shard_block(b, layout) for b in blocks]
    out = []
    for j, (i, _) in enumerate(per_shard[0]):
        out.append((i, jnp.concatenate(
            [per_shard[s][j][1] for s in range(layout.nshards)],
            axis=layout.dims[j])))
    return out


def gather_leaves(local_leaves, layout: ShardLayout, axis_name: str,
                  wire_dtype: Optional[str] = None,
                  act_wire: Optional[str] = None):
    """Inside a manual region: one tiled all_gather reassembling the full
    leaves from every rank's shard block (ZeRO-3 parameter gather; its
    autodiff transpose is the reduce_scatter that keeps gradients
    sharded). ``local_leaves`` are this rank's shard slices, in layout
    order. ``wire_dtype`` wire-casts the gathered buffer so the transposed
    reduce_scatter carries a quantized cotangent payload.

    ``act_wire`` (mp_comm activation wire) additionally quantizes the
    FORWARD payload itself — per-leaf absmax scales, a REAL
    reduced-precision all_gather in the compiled HLO, not just the
    cotangent cast (``collective.all_gather_quantized``)."""
    flat = jnp.concatenate([v.reshape(-1) for v in local_leaves])
    if act_wire in ("bf16", "int8"):
        from .collective import all_gather_quantized

        gathered = all_gather_quantized(
            flat, axis_name, wire_dtype=act_wire,
            segments=tuple(int(np.prod(v.shape)) if v.shape else 1
                           for v in local_leaves),
            grad_wire=wire_dtype)
        return unpack_gathered(gathered, layout)
    gathered = lax.all_gather(flat, axis_name, axis=0, tiled=True)
    if wire_dtype is not None:
        gathered = wire_cast(gathered, wire_dtype)
    return unpack_gathered(gathered, layout)


# ----------------------------------------------------------- mesh helpers --
def data_axes(mesh) -> Tuple[str, ...]:
    return tuple(a for a in ("dp", "sharding")
                 if a in mesh.shape and mesh.shape[a] > 1)


def is_pure_data_mesh(mesh) -> bool:
    """True when every non-trivial mesh axis is a data axis (dp/sharding):
    the whole step can run in one fully-manual region with no model
    parallelism or pipeline schedule inside."""
    if mesh is None or mesh.size <= 1:
        return False
    extent = 1
    for a in data_axes(mesh):
        extent *= mesh.shape[a]
    return extent == mesh.size


def spec_mentions(spec, axis_name: str) -> bool:
    for e in (spec or ()):
        if e == axis_name or (isinstance(e, (tuple, list)) and axis_name in e):
            return True
    return False


def sharded_dim(spec, axis_name: str) -> Optional[int]:
    """Dim index that ``spec`` shards over ``axis_name``, or None."""
    for i, e in enumerate(spec or ()):
        if e == axis_name or (isinstance(e, (tuple, list)) and axis_name in e):
            return i
    return None


# ------------------------------------------- explicit data-parallel step --
@dataclass(frozen=True)
class DpPlan:
    """Static exchange plan for the explicit data-parallel step: which
    trainable parameters ride shard-major ZeRO buckets (psum_scatter →
    shard-local update → all_gather) and which ride plain flat fusion
    buckets (psum → full update)."""

    axes: Tuple[str, ...]
    group: int
    nshards: int                      # extent of the `sharding` axis
    zero_layouts: Tuple[ShardLayout, ...]
    tail_layouts: Tuple[BucketLayout, ...]
    bytes_f32: int                    # one direction, f32 payload
    bytes_wire: int                   # same payload at the wire dtype
    overlap_tail: bool = False        # tail buckets issue in-backward

    @property
    def n_buckets(self) -> int:
        return len(self.zero_layouts) + len(self.tail_layouts)


def plan_dp_exchange(cfg: GradCommConfig, mesh, param_shapes,
                     param_itemsizes, trainable,
                     state_shard_dims) -> Optional[DpPlan]:
    """Build the bucket/shard plan, or None when the explicit path does not
    apply to this mesh. ``state_shard_dims[i]`` is the dim the committed
    optimizer state of param i is sharded over (None = replicated state)."""
    if not is_pure_data_mesh(mesh):
        return None
    axes = data_axes(mesh)
    group = int(np.prod([mesh.shape[a] for a in axes]))
    S = mesh.shape.get("sharding", 1)
    zero = cfg.zero_update and S > 1
    if S > 1 and not cfg.zero_update:
        # sharded optimizer states but no shard-local update: the explicit
        # path would have to gather states — strictly worse than GSPMD
        return None

    shardable, tail = [], []
    for i, (shape, tr, k) in enumerate(
            zip(param_shapes, trainable, state_shard_dims)):
        if not tr:
            continue
        if zero and k is not None and shape[k] % S == 0:
            shardable.append(i)
        else:
            tail.append(i)

    target = cfg.bucket_bytes
    zero_layouts = []
    if shardable:
        sizes = [int(np.prod(param_shapes[i])) * param_itemsizes[i]
                 for i in shardable]
        for g in build_buckets(sizes, target):
            idx = [shardable[j] for j in g]
            zero_layouts.append(make_shard_layout(
                idx, [param_shapes[i] for i in idx],
                [state_shard_dims[i] for i in idx], S))
    tail_layouts = []
    if tail:
        shapes = [param_shapes[i] for i in tail]
        its = [param_itemsizes[i] for i in tail]
        tail_layouts = list(make_layouts(shapes, its, target, indices=tail))

    n_elems = sum(l.total for l in zero_layouts) + sum(
        l.total for l in tail_layouts)
    return DpPlan(
        axes=axes, group=group, nshards=S,
        zero_layouts=tuple(zero_layouts), tail_layouts=tuple(tail_layouts),
        bytes_f32=n_elems * 4, bytes_wire=n_elems * cfg.wire_itemsize,
        overlap_tail=bool(cfg.overlap and tail_layouts
                          and not (cfg.quantized and cfg.error_feedback)),
    )


SUPPORTED_CLIPS = ("ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm")


def clip_supported(clip) -> bool:
    return clip is None or type(clip).__name__ in SUPPORTED_CLIPS


def _clip_sharded(clip, shard_pairs, tail_pairs, have_sharding: bool):
    """Apply a grad clip to (param_idx, shard_grad) + (param_idx, full_grad)
    pairs inside the manual region. Norms over sharded grads close over the
    `sharding` axis with a scalar/vector psum; full (tail) grads are
    replicated across the group so their norm contribution is added once."""
    kind = type(clip).__name__
    if kind == "ClipGradByValue":
        f = lambda g: jnp.clip(g, clip.min, clip.max)
        return ([(i, f(g)) for i, g in shard_pairs],
                [(i, f(g)) for i, g in tail_pairs])
    if kind == "ClipGradByNorm":
        out_s = []
        if shard_pairs:
            sq = jnp.stack([jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for _, g in shard_pairs])
            if have_sharding:
                sq = _psum_f32safe(sq, "sharding")
            norms = jnp.sqrt(sq)
            for j, (i, g) in enumerate(shard_pairs):
                scale = jnp.minimum(
                    clip.clip_norm / jnp.maximum(norms[j], 1e-12), 1.0)
                out_s.append((i, g * scale.astype(g.dtype)))
        out_t = []
        for i, g in tail_pairs:
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            scale = jnp.minimum(clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out_t.append((i, g * scale))
        return out_s, out_t
    # ClipGradByGlobalNorm
    sq_sh = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                for _, g in shard_pairs)
    if shard_pairs and have_sharding:
        sq_sh = _psum_f32safe(sq_sh, "sharding")
    sq_t = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
               for _, g in tail_pairs)
    gnorm = jnp.sqrt(sq_sh + sq_t)
    scale = clip.clip_norm / jnp.maximum(gnorm, clip.clip_norm)
    fix = lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype)
    return ([(i, fix(g)) for i, g in shard_pairs],
            [(i, fix(g)) for i, g in tail_pairs])


RESIDUAL_KEY = "__grad_comm__"


def init_residuals(cfg: GradCommConfig, plan: DpPlan, mesh):
    """Error-feedback residual buffers, one per bucket, committed sharded
    over the data axes (each group rank carries its own quantization
    error). NOT serialized with optimizer state — after restore the first
    step quantizes with a zero residual (documented in GRAD_COMM.md)."""
    from . import mesh as _mesh

    out = {}
    for b, lay in enumerate(tuple(plan.zero_layouts) + tuple(plan.tail_layouts)):
        z = jnp.zeros((plan.group, lay.total), jnp.float32)
        out[f"residual_{b}"] = _mesh.global_device_put(
            z, P(plan.axes if len(plan.axes) > 1 else plan.axes[0]), mesh)
    return out


def build_explicit_dp_step(cfg: GradCommConfig, plan: DpPlan, mesh, *,
                           loss_of, opt, trainable, state_specs_tree,
                           batch_spec_fn, buffer_changed_cell,
                           use_residuals: bool):
    """The explicit data-parallel train step: one fully-manual shard_map
    over the whole fwd+bwd+update, with the gradient exchange bucketed,
    optionally wire-quantized (+error feedback), and — when the `sharding`
    axis is live — decomposed into psum_scatter → shard-local optimizer
    update → all_gather of updated params (ZeRO weight-update sharding).

    Returns a ``step(p_vals, b_vals, opt_states, batch_vals, lr, rng_key)``
    with the same signature/state-layout contract as TrainStep._build_step
    (opt_states may carry a trailing {RESIDUAL_KEY: ...} entry)."""
    from .._jax_compat import shard_map as _shard_map

    axes = plan.axes
    S = plan.nshards
    have_sh = S > 1 and "sharding" in axes
    group = plan.group
    ef = use_residuals
    clip = getattr(opt, "_grad_clip", None)
    all_layouts = tuple(plan.zero_layouts) + tuple(plan.tail_layouts)
    # Backward-overlapped exchange (docs/PIPELINE.md §4): tail buckets wrap
    # their params in a custom_vjp identity whose backward packs the
    # bucket's cotangents, quantizes, and issues the group psum RIGHT THERE
    # — the returned "cotangent" IS the exchanged gradient, so XLA must
    # schedule the collective before any earlier layer's backward that
    # consumes nothing from it, i.e. it runs concurrently with the
    # remaining backward instead of after all of it. ZeRO buckets can't
    # ride this (psum_scatter yields shard-shaped grads, but a cotangent
    # must match the full param) and error feedback can't either (the
    # residual update is state escaping a vjp) — both keep the
    # post-backward issue.
    overlap_tail = plan.overlap_tail and not ef
    # mp_comm activation wire: the ZeRO parameter all-gather is a forward
    # payload, so it rides the quantized gather (floored at bf16 — see
    # MpCommConfig.param_gather_wire) when the activation wire is on
    from . import mp_comm as _mp_comm

    param_gather_wire = _mp_comm.resolve_config().param_gather_wire

    def _overlapped(shapes):
        @jax.custom_vjp
        def ident(*leaves):
            return leaves

        def fwd(*leaves):
            return leaves, None

        def bwd(_, cts):
            flat = jnp.concatenate(
                [c.astype(jnp.float32).reshape(-1) for c in cts])
            if cfg.quantized:
                flat = quantize_roundtrip(flat, cfg.wire_dtype)
            flat = lax.psum(flat, axes) / group
            out, off = [], 0
            for shp in shapes:
                n = int(np.prod(shp)) if shp else 1
                out.append(lax.dynamic_slice_in_dim(
                    flat, off, n, 0).reshape(shp))
                off += n
            return tuple(out)

        ident.defvjp(fwd, bwd)
        return ident

    def body(p_vals, b_vals, states, residuals, batch_vals, lr, rng_key):
        # decorrelate per-rank randomness (dropout) across the group
        ridx = jnp.int32(0)
        for a in axes:
            ridx = ridx * mesh.shape[a] + lax.axis_index(a)
        rng_local = jax.random.fold_in(rng_key, ridx)
        loss_fn = loss_of
        if overlap_tail:
            def loss_fn(p_list, aux):
                p_list = list(p_list)
                for lay in plan.tail_layouts:
                    ident = _overlapped(
                        [tuple(p_list[i].shape) for i in lay.indices])
                    for i, w in zip(lay.indices,
                                    ident(*[p_list[i] for i in lay.indices])):
                        p_list[i] = w
                return loss_of(p_list, aux)
        (loss, new_b), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            list(p_vals), (list(b_vals), list(batch_vals), rng_local))
        loss = lax.psum(loss.astype(jnp.float32), axes) / group
        # sync only buffers the model actually mutated (running stats):
        # identity of unchanged buffers survives the trace (see
        # DistTrainStep); untouched buffers stay replicated for free
        changed = buffer_changed_cell[0] if buffer_changed_cell else ()
        new_b = [
            _psum_f32safe(v, axes) / group
            if (ch and jnp.issubdtype(v.dtype, jnp.floating)) else v
            for v, ch in zip(new_b, changed or (False,) * len(new_b))
        ]

        shard_pairs, tail_pairs, new_res = [], [], {}
        for b, lay in enumerate(all_layouts):
            is_zero = b < len(plan.zero_layouts)
            if not is_zero and overlap_tail:
                # exchanged in-backward by the custom_vjp identity above:
                # grads[i] already carries the reduced (and, if quantized,
                # wire-round-tripped) group average
                tail_pairs.extend(
                    (i, grads[i].astype(jnp.float32)) for i in lay.indices)
                continue
            if is_zero:
                flat = pack_shard_major(grads, lay)
            else:
                flat = pack_bucket(grads, lay)
            flat32 = flat.astype(jnp.float32)
            if cfg.quantized:
                if ef:
                    flat32, res = quantize_with_feedback(
                        flat32, residuals[f"residual_{b}"][0], cfg.wire_dtype)
                    new_res[f"residual_{b}"] = res[None]
                else:
                    flat32 = quantize_roundtrip(flat32, cfg.wire_dtype)
            elif ef:
                new_res[f"residual_{b}"] = residuals[f"residual_{b}"]
            if is_zero:
                blk = flat32
                if have_sh:
                    blk = lax.psum_scatter(
                        blk, "sharding", scatter_dimension=0, tiled=True)
                if "dp" in axes:
                    blk = lax.psum(blk, "dp")
                blk = blk / group
                shard_pairs.extend(unpack_shard_block(blk, lay))
            else:
                flat32 = lax.psum(flat32, axes) / group
                tail_pairs.extend(unpack_bucket(flat32, lay))

        if clip is not None:
            shard_pairs, tail_pairs = _clip_sharded(
                clip, shard_pairs, tail_pairs, have_sh)

        # assemble aligned per-param lists for the (clip-free) update rule
        glist = [None] * len(p_vals)
        plist = list(p_vals)
        shard_dim = {}
        for lay in plan.zero_layouts:
            for i, k in zip(lay.indices, lay.dims):
                shard_dim[i] = k
        sidx = lax.axis_index("sharding") if have_sh else None
        for i, g in shard_pairs:
            k = shard_dim[i]
            glist[i] = g.astype(p_vals[i].dtype)
            chunk = p_vals[i].shape[k] // S
            plist[i] = lax.dynamic_slice_in_dim(
                p_vals[i], sidx * chunk, chunk, k)
        for i, g in tail_pairs:
            glist[i] = g.astype(p_vals[i].dtype)
        new_p, new_st = opt.functional_update(plist, glist, list(states), lr)

        # gather updated shards back to full params, one collective/bucket
        new_p = list(new_p)
        for lay in plan.zero_layouts:
            local = [new_p[i] for i in lay.indices]
            for i, full in gather_leaves(local, lay, "sharding",
                                         act_wire=param_gather_wire):
                new_p[i] = full
        return loss, tuple(new_p), tuple(new_b), list(new_st), new_res

    p_specs = [P()] * len(trainable)

    def step(p_vals, b_vals, opt_states, batch_vals, lr, rng_key):
        states, residuals = opt_states, {}
        if states and isinstance(states[-1], dict) and RESIDUAL_KEY in states[-1]:
            residuals = states[-1][RESIDUAL_KEY]
            states = states[:-1]
        b_specs = [P()] * len(b_vals)
        batch_specs = tuple(
            batch_spec_fn(tuple(v.shape)) for v in batch_vals)
        res_spec = P(axes if len(axes) > 1 else axes[0])
        res_specs = {k: res_spec for k in residuals}
        mapped = _shard_map(
            body, mesh=mesh,
            in_specs=(tuple(p_specs), tuple(b_specs), state_specs_tree,
                      res_specs, tuple(batch_specs), P(), P()),
            out_specs=(P(), tuple(p_specs), tuple(b_specs),
                       state_specs_tree, res_specs),
            axis_names=frozenset(axes), check_vma=False,
        )
        loss, new_p, new_b, new_st, new_res = mapped(
            tuple(p_vals), tuple(b_vals), list(states), residuals,
            tuple(batch_vals), lr, rng_key)
        new_st = list(new_st)
        if residuals:
            new_st.append({RESIDUAL_KEY: new_res})
        return loss, list(new_p), list(new_b), new_st

    return step


# ------------------------------------------------------------- metrics ----
def record_build_stats(n_buckets: int, payload_bytes_f32: int,
                       payload_bytes_wire: int) -> None:
    """Gauges describing the compiled gradient-exchange structure. Called
    at trace/build time (values are static Python numbers, never tracers).

    overlap_ratio: share of exchanged bytes NOT in the final-issued bucket.
    Buckets are built in parameter order and backward reaches bucket 0
    last, so everything outside bucket 0 can overlap remaining backward
    compute — 0.0 for a monolithic exchange, ->1 for many buckets."""
    _obs.set_gauge("grad_comm_buckets", float(n_buckets))
    if payload_bytes_f32 > 0:
        _obs.set_gauge("grad_comm_quantized_fraction",
                       1.0 - payload_bytes_wire / payload_bytes_f32)
    # instant marker span (dur 0): the build happens inside tracing, so
    # wall time is not separable here — the attrs are what matters
    _obs.record_span("grad_comm_exchange", dur_s=0.0, buckets=n_buckets,
                     wire_bytes=payload_bytes_wire)


def record_overlap_ratio(first_bucket_bytes: int, total_bytes: int) -> None:
    if total_bytes > 0:
        _obs.set_gauge("grad_comm_overlap_ratio",
                       1.0 - first_bucket_bytes / total_bytes)


def record_step_bytes(wire_bytes: int) -> None:
    """Per-executed-step wire payload (both directions of the exchange are
    counted by the caller)."""
    if wire_bytes > 0:
        _obs.inc("grad_comm_bytes_total", float(wire_bytes))
