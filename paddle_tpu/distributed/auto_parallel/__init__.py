"""Auto-parallel API (paddle.distributed auto_parallel parity).

Reference capability (SURVEY.md §2.3 "Auto-parallel"): `DistAttr`
(process_mesh + dims_mapping), `shard_tensor`, sharding completion/
partitioner/reshard passes over a static program
(`python/paddle/distributed/auto_parallel/`).

TPU-native design: this IS the native execution model — `shard_tensor` is a
device_put with a NamedSharding; "completion" (propagating shardings through
the graph) and "partitioner/reshard" (inserting collectives) are what GSPMD
does inside XLA for every jit'ed program. The API is therefore thin and
total: every op in the framework is auto-parallel by construction.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...framework.core import Tensor
from ...framework.op import raw
from .. import mesh as _mesh


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim


class Partial(Placement):
    """Pending-reduction placement. GSPMD tracks partial values internally;
    at the API boundary we reduce eagerly (a psum via resharding)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """paddle.distributed.ProcessMesh parity — wraps jax.sharding.Mesh."""

    def __init__(
        self,
        mesh: Union[Sequence, np.ndarray, None] = None,
        dim_names: Optional[Sequence[str]] = None,
        shape: Optional[Sequence[int]] = None,
        process_ids: Optional[Sequence[int]] = None,
    ):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids or range(len(jax.devices()))).reshape(
                shape or (-1,)
            )
        self._ids = arr
        self.shape = list(arr.shape)
        self.ndim = arr.ndim
        self.dim_names = list(dim_names) if dim_names else [f"d{i}" for i in range(arr.ndim)]
        self.process_ids = [int(i) for i in arr.ravel()]
        devs = np.asarray(jax.devices(), dtype=object)[arr.ravel()].reshape(arr.shape)
        self.jax_mesh = Mesh(devs, tuple(self.dim_names))

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self.process_ids == other.process_ids
            and self.shape == other.shape
        )

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _placements_to_spec(mesh: ProcessMesh, placements: Sequence[Placement], ndim: int) -> P:
    entries: List = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Shard):
            name = mesh.dim_names[axis_idx]
            if entries[pl.dim] is None:
                entries[pl.dim] = name
            elif isinstance(entries[pl.dim], tuple):
                entries[pl.dim] = entries[pl.dim] + (name,)
            else:
                entries[pl.dim] = (entries[pl.dim], name)
    return P(*entries)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement], dtype=None, **kwargs):
    """Place a tensor on a process mesh (paddle.distributed.shard_tensor)."""
    v = raw(data) if isinstance(data, Tensor) else jax.numpy.asarray(data)
    spec = _placements_to_spec(mesh, placements, v.ndim)
    out = jax.device_put(v, NamedSharding(mesh.jax_mesh, spec))
    t = Tensor(out, stop_gradient=getattr(data, "stop_gradient", True))
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Move a tensor to a new placement (reference: auto_parallel reshard —
    the comm-inserting pass; here a single resharding device_put / constraint)."""
    v = raw(tensor)
    spec = _placements_to_spec(mesh, placements, v.ndim)
    from ...framework.op import defop

    if any(isinstance(p, Partial) for p in placements):
        raise NotImplementedError("reshard to Partial is not supported")
    from ..mesh import sharding_constraint
    from ...framework.core import is_tracer_value

    if is_tracer_value(v):
        out = sharding_constraint(v, spec, mesh.jax_mesh)
    else:
        out = jax.device_put(v, NamedSharding(mesh.jax_mesh, spec))
    t = Tensor(out, stop_gradient=tensor.stop_gradient if isinstance(tensor, Tensor) else True)
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None, input_fn=None, output_fn=None):
    """Apply a user shard_fn(name, layer, mesh) over sublayers (paddle parity)."""
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    return layer


def get_mesh() -> Optional[ProcessMesh]:
    m = _mesh.get_global_mesh()
    if m is None:
        return None
    pm = ProcessMesh.__new__(ProcessMesh)
    pm.jax_mesh = m
    pm.shape = list(m.devices.shape)
    pm.ndim = m.devices.ndim
    pm.dim_names = list(m.axis_names)
    pm.process_ids = [d.id for d in m.devices.ravel()]
    pm._ids = np.asarray(pm.process_ids).reshape(pm.shape)
    return pm


def set_mesh(mesh: ProcessMesh):
    _mesh.set_global_mesh(mesh.jax_mesh)
